"""Protocol invariant checking at quiescence.

The reference has no consistency checking of any kind (SURVEY.md §5) —
its own debug strings suspect races ("Race condition?",
assignment.c:550) but nothing verifies cache/directory agreement.
These checks hold for the rebuilt fixture-semantics protocol once a
system is quiescent (all traces done, no in-flight messages, nobody
waiting); they do NOT hold mid-flight (the directory commits some
transitions optimistically before acks, assignment.c:230-231).

Checked invariants:

* **single-writer** — at most one cache holds an address in M or E.
* **directory shape** — EM has exactly one sharer bit, S at least one,
  U none.
* **owner recorded** — an M/E line's home directory is EM with exactly
  that node's bit set.
* **EM-reverse** — a quiescent EM entry whose recorded owner does not
  hold the line in M/E, while the owner's cache still carries the
  INVALID placeholder reserved for that address, marks a dropped
  ownership reply (REPLY_WR or an exclusive REPLY_RD): the directory
  committed the transfer but the data never arrived.  The placeholder
  condition matters — an owner that *evicted* its copy while the
  home's UPGRADE_NOTIFY promotion was in flight legally leaves an EM
  entry pointing at a node with no copy and no placeholder.
* **sharer recorded** — an S line's node appears in the home's sharer
  set, and the entry is S or EM (EM occurs transiently-legally when the
  home upgraded the last survivor whose line is now E; a genuinely
  SHARED line under an EM entry owned by someone else is a violation).
* **shared-value coherence** — an S line's value equals home memory
  (S fills come from memory or a FLUSH that also updated memory).
"""

from __future__ import annotations

from typing import List, Sequence

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import CacheState, DirState, INVALID_ADDR
from hpa2_tpu.utils.dump import NodeDump


def check_invariants(
    dumps: Sequence[NodeDump], config: SystemConfig,
    mid_flight: bool = False,
) -> List[str]:
    """Return a list of human-readable violations (empty = clean).

    ``dumps`` must be the *final quiescent* state of every node, in id
    order (``engine.final_dumps()``), not the per-node completion
    snapshots.

    ``mid_flight=True`` restricts the check to the directory-shape
    invariants, which hold at every cycle boundary (each handler leaves
    every entry it touches in a well-formed shape) — the subset safe
    for per-step debug checking and for watchdog diagnostics of a
    non-quiescent system, where cache/directory agreement is legally
    out of sync while acks are in flight.
    """
    v: List[str] = []
    n = config.num_procs
    if len(dumps) != n:
        return [f"need {n} dumps, got {len(dumps)}"]

    # collect cached copies per address
    holders = {}  # addr -> list[(node, state, value)]
    for d in dumps:
        for idx in range(config.cache_size):
            addr = d.cache_addr[idx]
            state = CacheState(d.cache_state[idx])
            if addr == INVALID_ADDR or state == CacheState.INVALID:
                continue
            holders.setdefault(addr, []).append(
                (d.proc_id, state, d.cache_value[idx])
            )

    if not mid_flight:
        for addr, hs in sorted(holders.items()):
            writers = [h for h in hs if h[1] in (CacheState.MODIFIED,
                                                 CacheState.EXCLUSIVE)]
            if len(writers) > 1:
                v.append(
                    f"single-writer violated at 0x{addr:02X}: {writers}"
                )
            if writers and len(hs) > 1:
                v.append(
                    f"M/E alongside other copies at 0x{addr:02X}: {hs}"
                )

    for home in range(n):
        d = dumps[home]
        for blk in range(config.mem_size):
            addr = config.make_addr(home, blk)
            ds = DirState(d.dir_state[blk])
            sharers = d.dir_sharers[blk]
            nbits = bin(sharers).count("1")
            if ds == DirState.EM and nbits != 1:
                v.append(
                    f"dir EM with {nbits} sharers at 0x{addr:02X} "
                    f"(home {home})"
                )
            elif ds == DirState.S and nbits < 1:
                v.append(f"dir S with no sharers at 0x{addr:02X}")
            elif ds == DirState.U and nbits != 0:
                v.append(f"dir U with sharers at 0x{addr:02X}")
            if mid_flight:
                continue

            hs = holders.get(addr, [])
            # EM-reverse (dropped-ack detector): a quiescent EM entry
            # promises its recorded owner holds the line in M/E.  A
            # lost REPLY_WR/REPLY_RD-exclusive leaves a precise
            # signature — the directory committed the ownership
            # transfer but the data never arrived, so the requester's
            # cache slot is still the INVALID placeholder it reserved
            # for the address.  Requiring the placeholder avoids the
            # legal eviction/UPGRADE_NOTIFY race, where the promoted
            # survivor evicted its copy and holds nothing at all.
            if ds == DirState.EM and nbits == 1:
                owner = sharers.bit_length() - 1
                od = dumps[owner]
                holds = any(
                    node == owner
                    and state in (CacheState.MODIFIED, CacheState.EXCLUSIVE)
                    for node, state, _ in hs
                )
                placeholder = any(
                    od.cache_addr[i] == addr
                    and CacheState(od.cache_state[i]) == CacheState.INVALID
                    for i in range(config.cache_size)
                )
                if not holds and placeholder:
                    v.append(
                        f"dir EM at 0x{addr:02X} records owner node "
                        f"{owner} but its cache still holds the INVALID "
                        "placeholder for the address (dropped ownership "
                        "reply?)"
                    )
            for node, state, value in hs:
                in_set = bool(sharers >> node & 1)
                if state in (CacheState.MODIFIED, CacheState.EXCLUSIVE):
                    if ds != DirState.EM or not in_set:
                        v.append(
                            f"{state.name} line at 0x{addr:02X} on node "
                            f"{node} but dir {ds.name} sharers "
                            f"0b{sharers:b}"
                        )
                elif state == CacheState.SHARED:
                    if ds == DirState.U or not in_set:
                        v.append(
                            f"SHARED line at 0x{addr:02X} on node {node} "
                            f"not in dir ({ds.name} 0b{sharers:b})"
                        )
                    if value != d.memory[blk]:
                        v.append(
                            f"SHARED value {value} != memory "
                            f"{d.memory[blk]} at 0x{addr:02X} node {node}"
                        )
    return v
