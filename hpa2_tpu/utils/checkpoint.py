"""Checkpoint / resume for simulator state.

The reference has no persistence at all (its only artifact is the
write-only ``printProcessorState`` dump, assignment.c:824-875 —
SURVEY.md §5 "checkpoint/resume: none").  Long benchmark runs on the
flaky TPU tunnel need one: ``SimState`` is a NamedTuple of arrays, so
a checkpoint is a single compressed ``.npz`` holding every leaf plus
the ``SystemConfig`` (JSON) that shaped them.  Works for single-system
and batched (leading ensemble axis) states alike — shapes carry the
difference.

Resume contract: ``load_state`` rebuilds a state tree that continues
bit-identically (tests/test_checkpoint.py gates interrupted-vs-straight
equality).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
from typing import Optional

import numpy as np

import jax.numpy as jnp

from hpa2_tpu.config import (
    FailureEvent,
    FailurePlan,
    FaultModel,
    InterconnectConfig,
    Semantics,
    SystemConfig,
)
from hpa2_tpu.ops.state import SimState

_MAGIC = "hpa2_checkpoint_v1"
_SPEC_MAGIC = "hpa2_spec_checkpoint_v1"

# Checkpoint metadata schema: v2 (ISSUE-16) adds the recovery counters
# to ``extra_meta["recovery"]``.  v1 files (no ``meta_version`` array)
# still load — the counters are zero-backfilled, mirroring the PR-15
# exchange-counter backfill for SimState fields.
_META_VERSION = 2

#: Supervisor recovery counters carried in checkpoint metadata since
#: schema v2; absent (= zero) in every older checkpoint.
RECOVERY_COUNTERS = ("migrations", "evacuations", "shed_jobs", "retries")

# Telemetry counters that may be absent from checkpoints written
# before they existed; zero-backfilled on load (all share n_msgs's
# shape — scalar, or [b] in batched states).  Every only-when-nonzero
# counter engine_stats() reads must appear here: the counter-backfill
# lint rule (analysis/lint.py) checks this file against ops/engine.py
# so the PR-15/PR-16 hand-patching never recurs.
_ZERO_BACKFILL = frozenset({
    # fault layer (ISSUE-9)
    "n_retrans", "n_dup_filtered", "n_reorder_fixed", "n_delays",
    "n_wire_stalls",
    # interconnect topology (ISSUE-11)
    "n_topo_delay", "n_multicast_saved", "n_combined",
    # cycle elision (ISSUE-12)
    "n_elided", "n_multi_hit",
    # protocol variants (ISSUE-13)
    "n_forwards", "n_owner_xfer", "n_dir_overflow",
    # cross-shard exchange (ISSUE-15)
    "n_exch_sent", "n_exch_hwm", "n_exch_mc_saved", "n_exch_combined",
})


def _config_to_json(config: SystemConfig) -> str:
    d = dataclasses.asdict(config)
    return json.dumps(d)


def _config_from_json(text: str) -> SystemConfig:
    d = json.loads(text)
    d["semantics"] = Semantics(**d["semantics"])
    if d.get("fault") is not None:
        d["fault"] = FaultModel(**d["fault"])
    if "interconnect" in d:  # absent in pre-topology checkpoints
        ic = dict(d["interconnect"])
        ic["fault"] = FaultModel(**ic["fault"])
        d["interconnect"] = InterconnectConfig(**ic)
    if d.get("failures") is not None:  # absent pre-ISSUE-16
        fp = dict(d["failures"])
        fp["events"] = tuple(
            FailureEvent(**ev) for ev in fp.get("events", ())
        )
        d["failures"] = FailurePlan(**fp)
    return SystemConfig(**d)


def save_state(
    path: str,
    state: SimState,
    config: SystemConfig,
    extra_meta: Optional[dict] = None,
) -> None:
    """Atomically write state + config (+ JSON-able workload metadata,
    e.g. batch/seed — checked on resume so a stale checkpoint from a
    different run can't be silently continued) to ``path`` (.npz)."""
    arrays = {
        f"f_{name}": np.asarray(leaf)
        for name, leaf in zip(SimState._fields, state)
    }
    arrays["meta_magic"] = np.array(_MAGIC)
    arrays["meta_version"] = np.array(_META_VERSION)
    arrays["meta_config"] = np.array(_config_to_json(config))
    extra = dict(extra_meta or {})
    # schema v2: the recovery counters always travel, zero-defaulted,
    # under extra["recovery"] so resumed runs keep their failover
    # history
    rec = dict(extra.get("recovery") or {})
    for name in RECOVERY_COUNTERS:
        rec.setdefault(name, 0)
    extra["recovery"] = rec
    arrays["meta_extra"] = np.array(json.dumps(extra))
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file


def load_state(path: str, with_meta: bool = False):
    """-> (state, config) or, with ``with_meta``, (state, config,
    extra_meta dict)."""
    with np.load(path) as z:
        if str(z["meta_magic"]) != _MAGIC:
            raise ValueError(f"{path}: not a hpa2 checkpoint")
        version = int(z["meta_version"]) if "meta_version" in z else 1
        if version > _META_VERSION:
            raise ValueError(
                f"{path}: checkpoint schema v{version} is newer than "
                f"this build's v{_META_VERSION}"
            )
        config = _config_from_json(str(z["meta_config"]))
        extra = json.loads(str(z["meta_extra"])) if "meta_extra" in z else {}
        # v1 files predate the recovery counters: zero-backfill so a
        # pre-failover checkpoint resumes exactly like a fresh v2 one
        rec = dict(extra.get("recovery") or {})
        for name in RECOVERY_COUNTERS:
            rec.setdefault(name, 0)
        extra["recovery"] = rec
        leaves = []
        for name in SimState._fields:
            key = f"f_{name}"
            if key not in z:
                if name in _ZERO_BACKFILL:
                    # telemetry counters added after the checkpoint was
                    # written — resume with zeros (batch shape follows
                    # an always-present scalar counter)
                    leaves.append(jnp.zeros_like(jnp.asarray(z["f_n_msgs"])))
                    continue
                raise ValueError(
                    f"{path}: missing field {name} (incompatible "
                    "checkpoint version)"
                )
            leaves.append(jnp.asarray(z[key]))
    state = SimState(*leaves)
    if with_meta:
        return state, config, extra
    return state, config


# -- spec-engine checkpointing (crash-resume) -------------------------
#
# The spec engine is plain Python objects, so its checkpoint is JSON:
# every node's architectural state (memory/directory/cache), the
# mailbox and backpressure queues mid-flight, the engine's schedule
# position, counters, logs, and — critically for fault injection —
# the exact ``random.Random`` state of the link-layer PRNG, so a run
# killed at cycle N and resumed continues on the *same* fault stream
# and finishes byte-identical to an uninterrupted run.


def _msg_to_list(m) -> list:
    return [int(m.type), m.sender, m.address, m.value, m.sharers,
            m.second_receiver, m.deliver_at]


def _msg_from_list(row) -> "object":
    from hpa2_tpu.models.protocol import Message, MsgType

    # pre-topology checkpoints have 6-element rows (no deliver_at)
    t, sender, address, value, sharers, second = row[:6]
    msg = Message(MsgType(t), sender, address, value, sharers, second)
    if len(row) > 6:
        msg.deliver_at = row[6]
    return msg


def _dump_to_dict(d) -> dict:
    out = {
        "proc_id": d.proc_id,
        "memory": list(d.memory),
        "dir_state": [int(s) for s in d.dir_state],
        "dir_sharers": list(d.dir_sharers),
        "cache_addr": list(d.cache_addr),
        "cache_value": list(d.cache_value),
        "cache_state": [int(s) for s in d.cache_state],
    }
    if d.dir_owner is not None:  # owner-plane protocols only
        out["dir_owner"] = list(d.dir_owner)
    return out


def _dump_from_dict(d) -> "object":
    from hpa2_tpu.models.protocol import CacheState, DirState
    from hpa2_tpu.utils.dump import NodeDump

    return NodeDump(
        proc_id=d["proc_id"],
        memory=list(d["memory"]),
        dir_state=[DirState(s) for s in d["dir_state"]],
        dir_sharers=list(d["dir_sharers"]),
        cache_addr=list(d["cache_addr"]),
        cache_value=list(d["cache_value"]),
        cache_state=[CacheState(s) for s in d["cache_state"]],
        dir_owner=(
            list(d["dir_owner"]) if d.get("dir_owner") is not None
            else None
        ),
    )


def save_spec_state(path: str, engine) -> None:
    """Atomically serialize a ``SpecEngine`` mid-run to ``path``
    (JSON).  Checkpoint at a cycle boundary (between ``step()`` calls);
    ``load_spec_state`` rebuilds an engine that continues
    bit-identically, fault stream included."""
    if engine._outbox:
        raise ValueError(
            "checkpoint only at a cycle boundary (outbox not drained)"
        )
    doc = {
        "magic": _SPEC_MAGIC,
        "config": json.loads(_config_to_json(engine.config)),
        "cycle": engine.cycle,
        "order_pos": engine.order_pos,
        "replay_batched": engine.replay_batched,
        "replay_order": (
            None if engine.replay_order is None
            else [dataclasses.astuple(r) for r in engine.replay_order]
        ),
        "counters": dict(engine.counters),
        "max_mailbox_depth": engine.max_mailbox_depth,
        "issue_log": [dataclasses.astuple(r) for r in engine.issue_log],
        "trace_msgs": engine.trace_msgs,
        "msg_log": list(engine.msg_log),
        "debug_invariants": engine.debug_invariants,
        "last_activity_cycle": engine.last_activity_cycle,
        "recent_msgs": [list(e) for e in engine.recent_msgs.entries()],
        "fault_rng": (
            None if engine._fault_rng is None
            else list(engine._fault_rng.getstate())
        ),
        "link_tracker": (
            None if engine.link_tracker is None
            else engine.link_tracker.dump_state()
        ),
        "nodes": [
            {
                "memory": list(n.memory),
                # 3-element rows carry the tracked owner pointer; the
                # loader accepts legacy 2-element (pre-protocol) rows
                "dir": [[int(e.state), e.sharers, e.owner]
                        for e in n.directory],
                "cache": [[l.address, l.value, int(l.state)]
                          for l in n.cache],
                "trace": [[i.op, i.address, i.value] for i in n.trace],
                "pc": n.pc,
                "waiting": n.waiting,
                "pending_write": n.pending_write,
                "mailbox": [_msg_to_list(m) for m in n.mailbox],
                "pending_sends": [
                    [ph, rcv, _msg_to_list(m)]
                    for ph, rcv, m in n.pending_sends
                ],
                "dumped": n.dumped,
                "snapshot": (
                    None if n.snapshot is None
                    else _dump_to_dict(n.snapshot)
                ),
                "dump_candidates": [
                    _dump_to_dict(d) for d in n.dump_candidates
                ],
            }
            for n in engine.nodes
        ],
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def load_spec_state(path: str):
    """Rebuild the ``SpecEngine`` saved by ``save_spec_state``."""
    from hpa2_tpu.models.protocol import CacheState, DirState, Instr
    from hpa2_tpu.models.spec_engine import SpecEngine
    from hpa2_tpu.utils.trace import IssueRecord

    with open(path) as f:
        doc = json.load(f)
    if doc.get("magic") != _SPEC_MAGIC:
        raise ValueError(f"{path}: not a hpa2 spec checkpoint")
    config = _config_from_json(json.dumps(doc["config"]))
    traces = [
        [Instr(op, addr, val) for op, addr, val in nd["trace"]]
        for nd in doc["nodes"]
    ]
    engine = SpecEngine(
        config,
        traces,
        replay_order=(
            None if doc["replay_order"] is None
            else [IssueRecord(*row) for row in doc["replay_order"]]
        ),
        replay_batched=doc["replay_batched"],
        trace_msgs=doc["trace_msgs"],
        debug_invariants=doc["debug_invariants"],
    )
    engine.cycle = doc["cycle"]
    engine.order_pos = doc["order_pos"]
    engine.counters.update(doc["counters"])
    engine.max_mailbox_depth = doc["max_mailbox_depth"]
    engine.issue_log = [IssueRecord(*row) for row in doc["issue_log"]]
    engine.msg_log = list(doc["msg_log"])
    engine.last_activity_cycle = doc["last_activity_cycle"]
    for entry in doc["recent_msgs"]:
        engine.recent_msgs.push(tuple(entry))
    if doc["fault_rng"] is not None:
        st = doc["fault_rng"]
        engine._fault_rng.setstate((st[0], tuple(st[1]), st[2]))
    if doc.get("link_tracker") is not None:
        engine.link_tracker.load_state(doc["link_tracker"])
    for node, nd in zip(engine.nodes, doc["nodes"]):
        node.memory = list(nd["memory"])
        for entry, row in zip(node.directory, nd["dir"]):
            entry.state = DirState(row[0])
            entry.sharers = row[1]
            # pre-protocol checkpoints have 2-element rows (no owner)
            entry.owner = row[2] if len(row) > 2 else -1
        for line, (addr, val, cs) in zip(node.cache, nd["cache"]):
            line.address = addr
            line.value = val
            line.state = CacheState(cs)
        node.pc = nd["pc"]
        node.waiting = nd["waiting"]
        node.pending_write = nd["pending_write"]
        node.mailbox.clear()
        node.mailbox.extend(_msg_from_list(r) for r in nd["mailbox"])
        node.pending_sends = [
            (ph, rcv, _msg_from_list(m))
            for ph, rcv, m in nd["pending_sends"]
        ]
        node.dumped = nd["dumped"]
        node.snapshot = (
            None if nd["snapshot"] is None
            else _dump_from_dict(nd["snapshot"])
        )
        node.dump_candidates = [
            _dump_from_dict(d) for d in nd["dump_candidates"]
        ]
    return engine


def latest_checkpoint(directory: str, stem: str = "ckpt") -> Optional[str]:
    """Highest-numbered ``<stem>_<n>.npz`` in ``directory`` (or None)."""
    best, best_n = None, -1
    if not os.path.isdir(directory):
        return None
    for name in os.listdir(directory):
        if not (name.startswith(stem + "_") and name.endswith(".npz")):
            continue
        try:
            n = int(name[len(stem) + 1 : -4])
        except ValueError:
            continue
        if n > best_n:
            best, best_n = os.path.join(directory, name), n
    return best
