"""Checkpoint / resume for simulator state.

The reference has no persistence at all (its only artifact is the
write-only ``printProcessorState`` dump, assignment.c:824-875 —
SURVEY.md §5 "checkpoint/resume: none").  Long benchmark runs on the
flaky TPU tunnel need one: ``SimState`` is a NamedTuple of arrays, so
a checkpoint is a single compressed ``.npz`` holding every leaf plus
the ``SystemConfig`` (JSON) that shaped them.  Works for single-system
and batched (leading ensemble axis) states alike — shapes carry the
difference.

Resume contract: ``load_state`` rebuilds a state tree that continues
bit-identically (tests/test_checkpoint.py gates interrupted-vs-straight
equality).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
from typing import Optional

import numpy as np

import jax.numpy as jnp

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.state import SimState

_MAGIC = "hpa2_checkpoint_v1"


def _config_to_json(config: SystemConfig) -> str:
    d = dataclasses.asdict(config)
    return json.dumps(d)


def _config_from_json(text: str) -> SystemConfig:
    d = json.loads(text)
    d["semantics"] = Semantics(**d["semantics"])
    return SystemConfig(**d)


def save_state(
    path: str,
    state: SimState,
    config: SystemConfig,
    extra_meta: Optional[dict] = None,
) -> None:
    """Atomically write state + config (+ JSON-able workload metadata,
    e.g. batch/seed — checked on resume so a stale checkpoint from a
    different run can't be silently continued) to ``path`` (.npz)."""
    arrays = {
        f"f_{name}": np.asarray(leaf)
        for name, leaf in zip(SimState._fields, state)
    }
    arrays["meta_magic"] = np.array(_MAGIC)
    arrays["meta_config"] = np.array(_config_to_json(config))
    arrays["meta_extra"] = np.array(json.dumps(extra_meta or {}))
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file


def load_state(path: str, with_meta: bool = False):
    """-> (state, config) or, with ``with_meta``, (state, config,
    extra_meta dict)."""
    with np.load(path) as z:
        if str(z["meta_magic"]) != _MAGIC:
            raise ValueError(f"{path}: not a hpa2 checkpoint")
        config = _config_from_json(str(z["meta_config"]))
        extra = json.loads(str(z["meta_extra"])) if "meta_extra" in z else {}
        leaves = []
        for name in SimState._fields:
            key = f"f_{name}"
            if key not in z:
                raise ValueError(
                    f"{path}: missing field {name} (incompatible "
                    "checkpoint version)"
                )
            leaves.append(jnp.asarray(z[key]))
    state = SimState(*leaves)
    if with_meta:
        return state, config, extra
    return state, config


def latest_checkpoint(directory: str, stem: str = "ckpt") -> Optional[str]:
    """Highest-numbered ``<stem>_<n>.npz`` in ``directory`` (or None)."""
    best, best_n = None, -1
    if not os.path.isdir(directory):
        return None
    for name in os.listdir(directory):
        if not (name.startswith(stem + "_") and name.endswith(".npz")):
            continue
        try:
            n = int(name[len(stem) + 1 : -4])
        except ValueError:
            continue
        if n > best_n:
            best, best_n = os.path.join(directory, name), n
    return best
