"""Trace / dump I/O, synthetic trace generators, comparison helpers."""

from hpa2_tpu.utils.dump import format_processor_state, parse_processor_dump
from hpa2_tpu.utils.trace import (
    load_core_trace,
    load_trace_dir,
    parse_instruction_order,
)

__all__ = [
    "format_processor_state",
    "parse_processor_dump",
    "load_core_trace",
    "load_trace_dir",
    "parse_instruction_order",
]
