"""Trace loading, instruction-order (replay) parsing, synthetic generators.

File formats are the reference's (README.md:55-68):

* ``tests/<dir>/core_<n>.txt`` — one instruction per line,
  ``RD <hexaddr>`` or ``WR <hexaddr> <decvalue>``.  The reference
  parses with ``sscanf("RD %hhx")`` / ``("WR %hhx %hhu")`` and caps at
  ``MAX_INSTR_NUM`` lines (assignment.c:802-818).  Deliberate loader
  deviations (all fail-fast where the reference corrupts silently):
  malformed non-blank lines raise instead of leaving uninitialized
  instruction slots; blank lines are skipped instead of counted; and
  addresses out of range for the config raise in ``load_trace_dir``
  instead of wrapping like ``%hhx`` (the reference would truncate
  ``0x115`` to ``0x15``).  Write values wrap mod 256 like ``%hhu``.
* ``instruction_order.txt`` — the recorded issue interleaving, i.e. the
  reference's DEBUG_INSTR stdout lines
  ``Processor %d: instr type=%c, address=0x%02X, value=%d``
  (assignment.c:595-598).  Multi-run fixture suites pair each output
  set with the order that produced it (SURVEY.md §4).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
from typing import List, Optional, Sequence, Tuple

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import Instr, MsgType


class TraceRing:
    """Bounded ring of recent interconnect deliveries for stall
    diagnostics (the "flight recorder" a watchdog dumps).

    Recording sits on the delivery hot path, so it is a bare tuple
    append into a bounded deque; formatting is deferred to
    :meth:`lines`, which only the diagnostic path calls.
    """

    def __init__(self, maxlen: int = 64):
        self.maxlen = maxlen
        self._ring: "collections.deque[Tuple[int, int, int, int, int]]" = (
            collections.deque(maxlen=maxlen)
        )

    def record(
        self, cycle: int, sender: int, receiver: int, mtype: int, address: int
    ) -> None:
        self._ring.append((cycle, sender, receiver, mtype, address))

    def push(self, entry: Tuple[int, int, int, int, int]) -> None:
        """Re-append a raw entry (checkpoint restore)."""
        self._ring.append(entry)

    def entries(self) -> List[Tuple[int, int, int, int, int]]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def lines(self) -> List[str]:
        return [
            f"cycle {c}: {s} -> {r} {MsgType(t).name} 0x{a:02X}"
            for c, s, r, t, a in self._ring
        ]

_RD_RE = re.compile(r"^RD\s+(?:0[xX])?([0-9a-fA-F]+)\s*$")
_WR_RE = re.compile(r"^WR\s+(?:0[xX])?([0-9a-fA-F]+)\s+(\d+)\s*$")
_ORDER_RE = re.compile(
    r"^Processor\s+(\d+):\s+instr type=([RW]),\s+address=0x([0-9a-fA-F]+),"
    r"\s+value=(\d+)\s*$"
)


def parse_core_trace(text: str, max_instr: Optional[int] = None) -> List[Instr]:
    """Parse one core trace. Values are bytes (sscanf %hhu, mod 256)."""
    instrs: List[Instr] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if max_instr is not None and len(instrs) >= max_instr:
            break
        m = _RD_RE.match(line)
        if m:
            instrs.append(Instr("R", int(m.group(1), 16)))
            continue
        m = _WR_RE.match(line)
        if m:
            instrs.append(Instr("W", int(m.group(1), 16), int(m.group(2)) % 256))
            continue
        raise ValueError(f"malformed trace line {lineno}: {raw!r}")
    return instrs


def load_core_trace(path: str, max_instr: Optional[int] = None) -> List[Instr]:
    with open(path, "r") as f:
        return parse_core_trace(f.read(), max_instr)


def load_trace_dir(
    trace_dir: str, config: SystemConfig
) -> List[List[Instr]]:
    """Load ``core_<n>.txt`` for every node (assignment.c:793-818).

    Missing files are an error for node ids that exist in the config,
    matching the reference (which exits if any core file is absent,
    assignment.c:796-800).
    """
    cap = config.max_instr_num if config.max_instr_num > 0 else None
    traces = []
    for n in range(config.num_procs):
        path = os.path.join(trace_dir, f"core_{n}.txt")
        trace = load_core_trace(path, cap)
        for i, instr in enumerate(trace):
            if not (0 <= instr.address < config.num_addresses):
                raise ValueError(
                    f"{path} instr {i}: address 0x{instr.address:X} out of "
                    f"range for {config.num_procs} nodes x "
                    f"{config.mem_size} blocks"
                )
        traces.append(trace)
    return traces


@dataclasses.dataclass(frozen=True)
class IssueRecord:
    """One line of instruction_order.txt."""

    proc: int
    op: str  # 'R' | 'W'
    address: int
    value: int


def parse_instruction_order(text: str) -> List[IssueRecord]:
    records = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        m = _ORDER_RE.match(line)
        if not m:
            raise ValueError(f"malformed order line {lineno}: {raw!r}")
        records.append(
            IssueRecord(
                proc=int(m.group(1)),
                op=m.group(2),
                address=int(m.group(3), 16),
                value=int(m.group(4)),
            )
        )
    return records


def load_instruction_order(path: str) -> List[IssueRecord]:
    with open(path, "r") as f:
        return parse_instruction_order(f.read())


def format_instruction_order(records: Sequence[IssueRecord]) -> str:
    """Inverse of :func:`parse_instruction_order` — the DEBUG_INSTR
    line format (assignment.c:596-597) used by every shipped
    ``instruction_order.txt`` fixture."""
    return "".join(
        f"Processor {r.proc}: instr type={r.op}, "
        f"address=0x{r.address:02X}, value={r.value}\n"
        for r in records
    )


def validate_order_against_traces(
    order: Sequence[IssueRecord], traces: Sequence[Sequence[Instr]]
) -> None:
    """Check a recorded order is exactly an interleaving of the traces."""
    cursors = [0] * len(traces)
    for i, rec in enumerate(order):
        if not (0 <= rec.proc < len(traces)):
            raise ValueError(
                f"order line {i}: processor id {rec.proc} out of range "
                f"(have {len(traces)} traces)"
            )
        tr = traces[rec.proc]
        if cursors[rec.proc] >= len(tr):
            raise ValueError(f"order line {i}: proc {rec.proc} trace exhausted")
        instr = tr[cursors[rec.proc]]
        if (instr.op, instr.address) != (rec.op, rec.address) or (
            instr.op == "W" and instr.value != rec.value
        ):
            raise ValueError(
                f"order line {i}: {rec} does not match trace instr {instr}"
            )
        cursors[rec.proc] += 1
    for p, c in enumerate(cursors):
        if c != len(traces[p]):
            raise ValueError(f"order incomplete: proc {p} at {c}/{len(traces[p])}")


# ---------------------------------------------------------------------------
# Synthetic trace generators (BASELINE.json configs)
# ---------------------------------------------------------------------------

def gen_uniform_random(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
    write_frac: float = 0.5,
) -> List[List[Instr]]:
    """Uniform-random RD/WR over the whole address space — the
    high-sharing / INV-storm workload (BASELINE.json config 3)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    traces = []
    for n in range(config.num_procs):
        ops = rng.random(instrs_per_core) < write_frac
        addrs = rng.integers(0, config.num_addresses, instrs_per_core)
        vals = rng.integers(0, 256, instrs_per_core)
        traces.append(
            [
                Instr("W", int(a), int(v)) if w else Instr("R", int(a))
                for w, a, v in zip(ops, addrs, vals)
            ]
        )
    return traces


def gen_producer_consumer(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
) -> List[List[Instr]]:
    """Neighbor producer/consumer sharing pattern (BASELINE.json
    config 4): node n writes its own blocks, reads node (n+1)'s."""
    import numpy as np

    rng = np.random.default_rng(seed)
    traces = []
    for n in range(config.num_procs):
        out: List[Instr] = []
        peer = (n + 1) % config.num_procs
        for i in range(instrs_per_core):
            blk = int(rng.integers(0, config.mem_size))
            if i % 2 == 0:
                out.append(
                    Instr("W", config.make_addr(n, blk), int(rng.integers(0, 256)))
                )
            else:
                out.append(Instr("R", config.make_addr(peer, blk)))
        traces.append(out)
    return traces


def gen_local_only(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
    write_frac: float = 0.5,
) -> List[List[Instr]]:
    """Node-local traffic only (the deterministic test_1/test_2 shape)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    traces = []
    for n in range(config.num_procs):
        ops = rng.random(instrs_per_core) < write_frac
        blks = rng.integers(0, config.mem_size, instrs_per_core)
        vals = rng.integers(0, 256, instrs_per_core)
        traces.append(
            [
                Instr("W", config.make_addr(n, int(b)), int(v))
                if w
                else Instr("R", config.make_addr(n, int(b)))
                for w, b, v in zip(ops, blks, vals)
            ]
        )
    return traces


def gen_hot_hit_zipf(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
    write_frac: float = 0.3,
    spread: float = 8.0,
    tail: float = 0.01,
) -> List[List[Instr]]:
    """Zipf-skewed private hot-set workload — the cycle-elision
    showcase (ISSUE-12; PERF.md "Cycle elision").

    Each node hammers a private hot set of ``cache_size``
    slot-distinct addresses in its own home slice (no conflict misses:
    every hot line keeps its direct-mapped slot for the whole run)
    with Zipf-like weights of max/min ratio ``spread``, plus a
    ``tail`` fraction of uniform-random addresses over the whole
    space.  After each node's cold misses settle, almost every access
    is a silent cache hit — exactly the run structure the event-driven
    engine retires in aggregated multi-hit steps — while the tail
    keeps a trickle of coherence traffic alive so the elision logic
    must keep proving quietness rather than assuming it.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    h = min(config.cache_size, config.mem_size)
    w = np.arange(1, h + 1, dtype=np.float64) ** -(
        np.log(spread) / np.log(float(h)) if h > 1 else 0.0
    )
    p = w / w.sum()
    traces = []
    for n in range(config.num_procs):
        hot = n * config.mem_size + np.arange(h)
        addrs = np.where(
            rng.random(instrs_per_core) < tail,
            rng.integers(0, config.num_addresses, instrs_per_core),
            hot[rng.choice(h, size=instrs_per_core, p=p)],
        )
        writes = rng.random(instrs_per_core) < write_frac
        vals = rng.integers(0, 256, instrs_per_core)
        traces.append(
            [
                Instr("W", int(a), int(v)) if is_w else Instr("R", int(a))
                for a, is_w, v in zip(addrs, writes, vals)
            ]
        )
    return traces


def gen_eviction_pingpong(
    config: SystemConfig,
    instrs_per_core: int,
    seed: int = 0,
    hot_homes: int = 2,
    write_frac: float = 0.1,
) -> List[List[Instr]]:
    """Adversarial liveness workload biased toward the reference's
    hang class (SURVEY.md §6.3; VERDICT round-4 item 8).

    Every generated address collides at cache index 0 (the test_4
    pattern — 0x00/0x20/0x30/0x3C all map to index 0,
    assignment.c:179, 603).  A few of them are "hot" lines that every
    node — *including their own home* — repeatedly reads, so homes
    become sharers of their own blocks; touching any other address
    evicts the hot line from the direct-mapped slot and sends
    EVICT_SHARED to its home.  The resulting eviction ping-pong +
    last-sharer upgrade-notify interleavings are exactly the class
    that livelocks reference HEAD (assignment.c:498-539) and that the
    NACK/UPGRADE_NOTIFY redesign must survive.
    """
    import numpy as np

    if config.cache_size > config.mem_size:
        raise ValueError(
            "gen_eviction_pingpong needs cache_size <= mem_size "
            "(index-0 collisions must exist in every home's slice)"
        )
    rng = np.random.default_rng(seed)
    n, c, m = config.num_procs, config.cache_size, config.mem_size

    def index0_block(home: int) -> int:
        # smallest b with (home*m + b) % c == 0; b < c <= m
        return (-home * m) % c

    homes = rng.permutation(n)[: max(1, min(hot_homes, n))]
    hot = [config.make_addr(int(h), index0_block(int(h))) for h in homes]
    colliders = [
        config.make_addr(h, b)
        for h in range(n)
        for b in range(index0_block(h), m, c)
        if config.make_addr(h, b) not in hot
    ]
    if not colliders:  # degenerate geometry (m == c, every home hot)
        colliders = hot
    traces = []
    for _ in range(n):
        tr = []
        for _ in range(instrs_per_core):
            r = rng.random()
            if r < write_frac:
                tr.append(
                    Instr("W", int(rng.choice(hot)),
                          int(rng.integers(0, 256)))
                )
            elif r < 0.65:
                tr.append(Instr("R", int(rng.choice(hot))))
            else:
                tr.append(Instr("R", int(rng.choice(colliders))))
        traces.append(tr)
    return traces


def gen_eviction_pingpong_arrays(
    config: SystemConfig,
    batch: int,
    instrs_per_core: int,
    seed: int = 0,
    **kw,
):
    """Batched :func:`gen_eviction_pingpong` as ``[B, N, T]`` arrays."""
    return traces_to_arrays(
        config,
        [
            gen_eviction_pingpong(config, instrs_per_core,
                                  seed=seed + b, **kw)
            for b in range(batch)
        ],
    )


def gen_uniform_random_arrays(
    config: SystemConfig,
    batch: int,
    instrs_per_core: int,
    seed: int = 0,
    write_frac: float = 0.5,
):
    """Vectorized batched uniform-random workload as ``[B, N, T]``
    numpy arrays (op 0=RD/1=WR, addr, value) + ``[B, N]`` lengths —
    the input format of ``ops.state.init_state_batched`` (building
    large ensembles through per-instruction Python objects is orders
    of magnitude too slow)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    shape = (batch, config.num_procs, instrs_per_core)
    op = (rng.random(shape) < write_frac).astype(np.int32)
    addr = rng.integers(
        0, config.num_addresses, shape, dtype=np.int32
    )
    val = rng.integers(0, 256, shape, dtype=np.int32)
    length = np.full(
        (batch, config.num_procs), instrs_per_core, dtype=np.int32
    )
    return op, addr, val, length


def heterogeneous_lengths(
    batch: int,
    max_instrs: int,
    dist: str = "zipf",
    spread: float = 8.0,
    seed: int = 0,
):
    """Per-system trace lengths for a heterogeneous ensemble workload.

    ``zipf``: lengths are ``floor * k`` for ``k ~ Zipf(2)``, clipped to
    ``[floor, max_instrs]`` with ``floor = max_instrs / spread`` — most
    systems run the shortest trace while a heavy tail of stragglers
    runs up to ``spread`` times longer (median ~= floor, so max/median
    ~= spread: the occupancy-collapse shape).  ``uniform``: lengths
    uniform over ``[floor, max_instrs]``.  The first system always gets
    ``max_instrs`` so the nominal geometry is exercised.  Shared by the
    workload generator below and the static occupancy model
    (hpa2_tpu/analysis/occupancy.py), so model inputs match generated
    workloads exactly.
    """
    import numpy as np

    if max_instrs < 1 or batch < 1:
        raise ValueError("batch and max_instrs must be >= 1")
    if spread < 1:
        raise ValueError(f"spread must be >= 1, got {spread}")
    floor = max(1, int(round(max_instrs / spread)))
    rng = np.random.default_rng(seed)
    if dist == "zipf":
        k = rng.zipf(2.0, size=batch)
        lens = np.clip(floor * k, floor, max_instrs)
    elif dist == "uniform":
        lens = rng.integers(floor, max_instrs + 1, size=batch)
    else:
        raise ValueError(f"dist must be 'uniform' or 'zipf', got {dist!r}")
    lens = lens.astype(np.int64)
    lens[rng.integers(0, batch)] = max_instrs
    return lens


def gen_heterogeneous_random_arrays(
    config: SystemConfig,
    batch: int,
    max_instrs: int,
    dist: str = "zipf",
    spread: float = 8.0,
    seed: int = 0,
    write_frac: float = 0.5,
):
    """:func:`gen_uniform_random_arrays` with heterogeneous per-system
    trace lengths from :func:`heterogeneous_lengths` — the occupancy
    scheduler's target workload (``bench.py --trace-len-dist``)."""
    import numpy as np

    op, addr, val, _ = gen_uniform_random_arrays(
        config, batch, max_instrs, seed=seed, write_frac=write_frac
    )
    lens = heterogeneous_lengths(batch, max_instrs, dist, spread, seed)
    length = np.broadcast_to(
        lens[:, None], (batch, config.num_procs)
    ).astype(np.int32).copy()
    return op, addr, val, length


def gen_producer_consumer_arrays(
    config: SystemConfig,
    batch: int,
    instrs_per_core: int,
    seed: int = 0,
):
    """Vectorized :func:`gen_producer_consumer` as ``[B, N, T]`` arrays
    (BASELINE.json config 4 at scale: node n writes its own blocks and
    reads node n+1's — the widened-bitVector sharing pattern)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n, t = config.num_procs, instrs_per_core
    shape = (batch, n, t)
    blk = rng.integers(0, config.mem_size, shape, dtype=np.int32)
    val = rng.integers(0, 256, shape, dtype=np.int32)
    node = np.arange(n, dtype=np.int32)[None, :, None]
    write = (np.arange(t, dtype=np.int32)[None, None, :] % 2) == 0
    op = np.broadcast_to(write, shape).astype(np.int32)
    home = np.where(write, node, (node + 1) % n)
    addr = home * config.mem_size + blk
    length = np.full((batch, n), t, dtype=np.int32)
    return op, addr, val, length


def traces_to_arrays(config: SystemConfig, batch_traces):
    """[[Instr]] per system -> ([B,N,T] op/addr/val, [B,N] len) arrays
    (the input format of the batched/Pallas engines)."""
    import numpy as np

    b = len(batch_traces)
    n = config.num_procs
    t = max(
        (len(tr) for traces in batch_traces for tr in traces), default=1
    )
    op = np.full((b, n, t), -1, np.int32)
    addr = np.zeros((b, n, t), np.int32)
    val = np.zeros((b, n, t), np.int32)
    length = np.zeros((b, n), np.int32)
    for bi, traces in enumerate(batch_traces):
        if len(traces) != n:
            raise ValueError(f"system {bi}: need {n} traces")
        for ni, tr in enumerate(traces):
            length[bi, ni] = len(tr)
            for j, ins in enumerate(tr):
                op[bi, ni, j] = 0 if ins.op == "R" else 1
                addr[bi, ni, j] = ins.address
                val[bi, ni, j] = ins.value
    return op, addr, val, length
