"""Fixture-parity harness: replay a recorded run and diff against fixtures.

A fixture *run set* is a directory holding ``core_<n>_output.txt`` for
every node plus the ``instruction_order.txt`` that produced it
(SURVEY.md §4).  Deterministic suites (sample, test_1, test_2) keep
these next to the traces; nondeterministic suites ship several run sets
(test_3/run_1..2, test_4/run_1..4).
"""

from __future__ import annotations

import difflib
import os
from typing import Dict, List, Optional, Sequence, Tuple

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.utils.dump import NodeDump, format_processor_state
from hpa2_tpu.utils.trace import load_instruction_order, load_trace_dir


def discover_run_sets(suite_dir: str) -> List[str]:
    """Directories containing fixture dumps + instruction_order.txt."""
    runs = sorted(
        os.path.join(suite_dir, d)
        for d in os.listdir(suite_dir)
        if d.startswith("run_") and os.path.isdir(os.path.join(suite_dir, d))
    )
    return runs if runs else [suite_dir]


def replay_run_set(
    suite_dir: str,
    run_dir: str,
    config: SystemConfig,
    engine_cls=SpecEngine,
    batched: bool = False,
):
    """Replay one run set with any parity-capable engine.

    SpecEngine captures dump candidates natively; JaxEngine does so via
    its cycle-stepping ``run_capturing_candidates`` mode.
    """
    traces = load_trace_dir(suite_dir, config)
    order = load_instruction_order(os.path.join(run_dir, "instruction_order.txt"))
    if issubclass(engine_cls, SpecEngine):
        engine = engine_cls(
            config, traces, replay_order=order, replay_batched=batched
        )
        engine.run()
    else:
        if batched:
            raise ValueError("batched replay is a SpecEngine-only mode")
        engine = engine_cls(config, traces, replay_order=order)
        engine.run_capturing_candidates()
    return engine


def engine_candidates(engine, node_id: int) -> List[NodeDump]:
    """Legal dump-timing candidates for one node, engine-agnostic."""
    if hasattr(engine, "nodes"):  # SpecEngine
        return list(engine.nodes[node_id].dump_candidates)
    return list(engine.dump_candidates[node_id])  # JaxEngine


def diff_against_fixtures(
    engine: SpecEngine,
    run_dir: str,
    config: SystemConfig,
    allow_candidates: bool = True,
) -> Dict[int, str]:
    """Return {node_id: unified diff} for every mismatching node.

    With ``allow_candidates`` a node matches if *any* of its legal
    dump-timing candidates (see ``Node.dump_candidates``) reproduces
    the fixture byte-exactly — the reference's dump moment is
    OS-scheduling-dependent, so the fixture pins one of several legal
    snapshots.  The reported diff is against the earliest (canonical)
    snapshot.
    """
    diffs: Dict[int, str] = {}
    snapshots = None  # lazy: only needed when a node has no candidates
    for node_id in range(config.num_procs):
        path = os.path.join(run_dir, f"core_{node_id}_output.txt")
        with open(path, "r") as f:
            expected = f.read()
        candidates = engine_candidates(engine, node_id) if allow_candidates else []
        if not candidates:
            if snapshots is None:
                snapshots = engine.snapshots()
            candidates = [snapshots[node_id]]
        rendered = [format_processor_state(c, config) for c in candidates]
        if expected not in rendered:
            diffs[node_id] = "".join(
                difflib.unified_diff(
                    expected.splitlines(keepends=True),
                    rendered[0].splitlines(keepends=True),
                    fromfile=f"fixture/{os.path.basename(run_dir)}/core_{node_id}",
                    tofile="engine",
                )
            )
    return diffs


def check_suite(
    suite_dir: str,
    config: SystemConfig,
    engine_cls=SpecEngine,
    batched: bool = False,
    allow_candidates: bool = True,
) -> Dict[str, Dict[int, str]]:
    """Replay every run set of a suite; return {run_dir: diffs}."""
    results = {}
    for run_dir in discover_run_sets(suite_dir):
        engine = replay_run_set(suite_dir, run_dir, config, engine_cls, batched)
        results[run_dir] = diff_against_fixtures(
            engine, run_dir, config, allow_candidates
        )
    return results
