"""Byte-exact `core_<n>_output.txt` state dumps, plus the inverse parser.

The text format is frozen by the reference's ``printProcessorState``
(assignment.c:824-875) and is the evaluation boundary (README.md:74:
"EVALUATION WILL BE BASED OFF OF THIS OUTPUT").  One deliberate
difference from reference HEAD: the sharer bitmask is rendered as
**binary digits** (``0x00000011`` = sharers {0,1}) — the convention
every shipped fixture uses — where HEAD prints the raw byte in hex
(assignment.c:858-860 vs tests/sample/core_1_output.txt; SURVEY.md
§6.2 item 1).

The parser inverts the formatter so fixtures and fresh dumps can be
compared structurally (and the formatter can be round-trip tested
against the shipped fixtures byte for byte).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import CacheState, DirState, INVALID_ADDR

#: Render order matches the reference enums (assignment.c:826-828);
#: the protocol-variant states append after the frozen MESI values.
_CACHE_STATE_STR = ["MODIFIED", "EXCLUSIVE", "SHARED", "INVALID",
                    "OWNED", "FORWARD"]
_DIR_STATE_STR = ["EM", "S", "U", "SO"]

#: The reference's empty-line sentinel byte (assignment.c:785-787).
_SENTINEL_BYTE = 0xFF


@dataclasses.dataclass
class NodeDump:
    """Parsed/parseable view of one node's dump."""

    proc_id: int
    memory: List[int]                       # [mem_size]
    dir_state: List[DirState]               # [mem_size]
    dir_sharers: List[int]                  # [mem_size] bitmask
    cache_addr: List[int]                   # [cache_size] (INVALID_ADDR = empty)
    cache_value: List[int]                  # [cache_size]
    cache_state: List[CacheState]           # [cache_size]
    # tracked owner/forwarder pointer per block (-1 = none); populated
    # only by owner-plane protocols (MOESI/MESIF) so MESI dumps stay
    # field-for-field identical to the reference format
    dir_owner: Optional[List[int]] = None   # [mem_size]


def _render_sharers(mask: int, width: int = 8) -> str:
    """Binary-digit rendering used by every shipped fixture:
    sharers {1,3} -> '00001010' (SURVEY.md §6.2 item 1)."""
    if mask < 0:
        raise ValueError("negative sharer mask")
    digits = format(mask, "b").zfill(width)
    if len(digits) > width:
        raise ValueError(
            f"sharer mask 0x{mask:x} needs more than {width} binary digits; "
            "use the wide dump format for num_procs > 8"
        )
    return digits


def format_processor_state(dump: NodeDump, config: SystemConfig) -> str:
    """Byte-exact re-creation of printProcessorState (assignment.c:824-875)."""
    if not config.parity_compatible:
        return _format_wide(dump, config)

    out: List[str] = []
    pid = dump.proc_id
    out.append("=======================================\n")
    out.append(f" Processor Node: {pid}\n")
    out.append("=======================================\n\n")

    # Memory table (assignment.c:844-851)
    out.append("-------- Memory State --------\n")
    out.append("| Index | Address |   Value  |\n")
    out.append("|----------------------------|\n")
    for i in range(config.mem_size):
        addr = config.make_addr(pid, i)
        out.append(f"|  {i:3d}  |  0x{addr:02X}   |  {dump.memory[i]:5d}   |\n")
    out.append("------------------------------\n\n")

    # Directory table (assignment.c:854-862) with fixture-style
    # binary bitVector rendering.
    out.append("------------ Directory State ---------------\n")
    out.append("| Index | Address | State |    BitVector   |\n")
    out.append("|------------------------------------------|\n")
    for i in range(config.mem_size):
        addr = config.make_addr(pid, i)
        state = _DIR_STATE_STR[int(dump.dir_state[i])]
        vec = _render_sharers(dump.dir_sharers[i])
        out.append(f"|  {i:3d}  |  0x{addr:02X}   |  {state:>2s}   |   0x{vec}   |\n")
    out.append("--------------------------------------------\n\n")

    # Cache table (assignment.c:865-873) — note the literal space+tab
    # before the closing pipe.
    out.append("------------ Cache State ----------------\n")
    out.append("| Index | Address | Value |    State    |\n")
    out.append("|---------------------------------------|\n")
    for i in range(config.cache_size):
        addr = dump.cache_addr[i]
        byte_addr = _SENTINEL_BYTE if addr == INVALID_ADDR else addr
        state = _CACHE_STATE_STR[int(dump.cache_state[i])]
        out.append(
            f"|  {i:3d}  |  0x{byte_addr:02X}   |  {dump.cache_value[i]:3d}  |  {state:>8s} \t|\n"
        )
    out.append("----------------------------------------\n\n")
    return "".join(out)


def _format_wide(dump: NodeDump, config: SystemConfig) -> str:
    """Scalable dump format for geometries the reference text format
    cannot express (num_procs > 8 or mem_size != 16).  Same tables,
    wider fields, hex sharer words."""
    out: List[str] = []
    pid = dump.proc_id
    words = config.sharer_words
    out.append(f"# hpa2 node dump (wide format) proc={pid} "
               f"nodes={config.num_procs} mem={config.mem_size} "
               f"cache={config.cache_size}\n")
    out.append("[memory]\n")
    for i in range(config.mem_size):
        out.append(f"{i} {config.make_addr(pid, i):#x} {dump.memory[i]}\n")
    out.append("[directory]\n")
    for i in range(config.mem_size):
        mask = dump.dir_sharers[i]
        hexwords = ",".join(
            f"{(mask >> (32 * w)) & 0xFFFFFFFF:08x}" for w in range(words)
        )
        owner = (
            f" own={dump.dir_owner[i]}" if dump.dir_owner is not None else ""
        )
        out.append(
            f"{i} {config.make_addr(pid, i):#x} "
            f"{_DIR_STATE_STR[int(dump.dir_state[i])]} {hexwords}{owner}\n"
        )
    out.append("[cache]\n")
    for i in range(config.cache_size):
        addr = dump.cache_addr[i]
        addr_s = "-" if addr == INVALID_ADDR else f"{addr:#x}"
        out.append(
            f"{i} {addr_s} {dump.cache_value[i]} "
            f"{_CACHE_STATE_STR[int(dump.cache_state[i])]}\n"
        )
    return "".join(out)


# ---------------------------------------------------------------------------
# Parsing (inverse of the parity format)
# ---------------------------------------------------------------------------

_MEM_ROW = re.compile(
    r"^\|\s*(\d+)\s*\|\s*0x([0-9A-Fa-f]{2})\s*\|\s*(\d+)\s*\|$"
)
_DIR_ROW = re.compile(
    r"^\|\s*(\d+)\s*\|\s*0x([0-9A-Fa-f]{2})\s*\|\s*(EM|S|U)\s*\|\s*0x([01]{8})\s*\|$"
)
# reference HEAD prints the sharer byte as raw hex via 0x%08X
# (assignment.c:858-860) instead of the fixtures' binary digits
_DIR_ROW_HEX = re.compile(
    r"^\|\s*(\d+)\s*\|\s*0x([0-9A-Fa-f]{2})\s*\|\s*(EM|S|U)\s*\|\s*0x([0-9A-Fa-f]{8})\s*\|$"
)
_CACHE_ROW = re.compile(
    r"^\|\s*(\d+)\s*\|\s*0x([0-9A-Fa-f]{2})\s*\|\s*(\d+)\s*\|\s*"
    r"(MODIFIED|EXCLUSIVE|SHARED|INVALID)\s*\t\|$"
)
_PROC_LINE = re.compile(r"^ Processor Node: (\d+)$")


def parse_processor_dump(text: str, sharers_hex: bool = False) -> NodeDump:
    """Parse a parity-format dump (fixture or fresh) back into NodeDump.

    ``sharers_hex=True`` reads the bitVector column as the raw hex
    byte reference HEAD prints (assignment.c:858-860) instead of the
    fixtures' binary-digit rendering — for ingesting dumps produced by
    the actual reference binary in HEAD-differential studies."""
    proc_id = None
    memory: List[int] = []
    dir_state: List[DirState] = []
    dir_sharers: List[int] = []
    cache_addr: List[int] = []
    cache_value: List[int] = []
    cache_state: List[CacheState] = []

    section = None
    for line in text.splitlines():
        m = _PROC_LINE.match(line)
        if m:
            proc_id = int(m.group(1))
            continue
        if line.startswith("-------- Memory State"):
            section = "mem"
            continue
        if line.startswith("------------ Directory State"):
            section = "dir"
            continue
        if line.startswith("------------ Cache State"):
            section = "cache"
            continue
        if section == "mem":
            m = _MEM_ROW.match(line)
            if m:
                memory.append(int(m.group(3)))
        elif section == "dir":
            m = (_DIR_ROW_HEX if sharers_hex else _DIR_ROW).match(line)
            if m:
                dir_state.append(DirState[m.group(3)])
                dir_sharers.append(int(m.group(4), 2 if not sharers_hex else 16))
        elif section == "cache":
            m = _CACHE_ROW.match(line)
            if m:
                addr = int(m.group(2), 16)
                cache_addr.append(INVALID_ADDR if addr == _SENTINEL_BYTE else addr)
                cache_value.append(int(m.group(3)))
                cache_state.append(CacheState[m.group(4)])

    if proc_id is None or not memory or not dir_state or not cache_addr:
        raise ValueError("not a recognizable processor dump")
    if len(memory) != len(dir_state):
        raise ValueError(
            f"malformed dump: {len(memory)} memory rows but "
            f"{len(dir_state)} directory rows (a row failed to parse?)"
        )
    return NodeDump(
        proc_id=proc_id,
        memory=memory,
        dir_state=dir_state,
        dir_sharers=dir_sharers,
        cache_addr=cache_addr,
        cache_value=cache_value,
        cache_state=cache_state,
    )
