"""Directory sharer-set format variants.

The directory keeps an exact internal bitvector in every format — the
format governs only what the home *believes* when composing an
invalidation fan-out (the REPLY_ID sharer mask).  That is where real
limited-pointer / coarse-vector directories lose precision, and the
protocol absorbs the resulting spurious INVs through the existing
stale-INV drop rows:

* ``full``       — exact bitvector (the reference's 1-byte bitVector,
                   generalized to arbitrary width).
* ``limited:K``  — up to K precise pointers; a fan-out over more than K
                   sharers overflows to broadcast (all nodes minus the
                   requester) and bumps ``n_dir_overflow``.
* ``coarse:G``   — one presence bit per G-node group; a fan-out INVs
                   every member of every group containing a sharer.

Formats apply identically in the spec engine (``dir_mask_int``) and the
JAX kernels (via ``group_mask_words`` constants + popcount) so the
backends stay differentially comparable.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from hpa2_tpu.models.protocol import bit, count_sharers

DIRECTORY_FORMATS = ("full", "limited", "coarse")


def parse_format(fmt: str, num_procs: int) -> Tuple[str, Optional[int]]:
    """Parse/validate a ``Config.directory_format`` string.

    Returns ``(kind, param)``: ``("full", None)``, ``("limited", K)``
    or ``("coarse", G)``.  Raises ``ValueError`` with a loud message on
    an unknown format or a parameter incompatible with ``num_procs``.
    """
    if fmt == "full":
        return ("full", None)
    for kind in ("limited", "coarse"):
        if fmt == kind or fmt.startswith(kind + ":"):
            raw = fmt[len(kind) + 1:] if ":" in fmt else ""
            try:
                param = int(raw)
            except ValueError:
                raise ValueError(
                    f"directory_format {fmt!r}: expected {kind}:<int>")
            if kind == "limited" and not 1 <= param < num_procs:
                raise ValueError(
                    f"directory_format {fmt!r}: pointer count must be in "
                    f"[1, num_procs) = [1, {num_procs}); a limited "
                    f"directory with >= num_procs pointers is just full")
            if kind == "coarse" and not 2 <= param < num_procs:
                raise ValueError(
                    f"directory_format {fmt!r}: group size must be in "
                    f"[2, num_procs) = [2, {num_procs}); groups of 1 are "
                    f"full precision, one all-node group is broadcast")
            return (kind, param)
    raise ValueError(
        f"unknown directory_format {fmt!r}; expected one of "
        f"'full', 'limited:<K>', 'coarse:<G>'")


def dir_mask_int(
    kind: str,
    param: Optional[int],
    sharers: int,
    requester: int,
    num_procs: int,
) -> Tuple[int, bool]:
    """Spec-engine fan-out mask: (mask, overflowed).

    ``sharers`` is the exact internal bitvector; the result is the set
    the home actually invalidates (requester always excluded).
    """
    base = sharers & ~bit(requester)
    if kind == "full":
        return base, False
    if kind == "limited":
        if count_sharers(base) > param:
            all_mask = (1 << num_procs) - 1
            return all_mask & ~bit(requester), True
        return base, False
    # coarse: spread every set bit over its G-aligned group
    out = 0
    for g0 in range(0, num_procs, param):
        gm = ((1 << min(param, num_procs - g0)) - 1) << g0
        if base & gm:
            out |= gm
    return out & ~bit(requester), False


def group_mask_words(
    param: int, num_procs: int, words: int, word_bits: int,
) -> np.ndarray:
    """[n_groups, words] int32 group-member masks for the JAX coarse
    transform (trace-time constants)."""
    n_groups = (num_procs + param - 1) // param
    out = np.zeros((n_groups, words), dtype=np.int32)
    for g in range(n_groups):
        for p in range(g * param, min((g + 1) * param, num_procs)):
            out[g, p // word_bits] |= 1 << (p % word_bits)
    return out
