"""Lower a ``TransitionTable`` into the planes the kernels execute.

The lowering is *derivational*: every field of ``ProtocolPlanes`` is
computed from table rows (which states answer a WRITEBACK_INT, what a
REPLY_RD flag fills, which states evict dirty, ...), never restated by
hand.  Mutating a row therefore changes the compiled planes, and
through them the spec engine's guards and the JAX/Pallas transition
masks — the property the cross-protocol mutation fuzzing leans on.

``planes_for`` is cached on (protocol, semantics) and runs the full
static check suite as a build-time gate: a table that fails
completeness/determinism/no-silent-drop/state-product/reply-guarantee
never reaches a kernel.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
from typing import Dict, Sequence, Tuple

from hpa2_tpu.config import Semantics
from hpa2_tpu.models.protocol import (
    CacheState,
    DirState,
    MsgType,
    REPLY_RD_EXCLUSIVE,
    REPLY_RD_FORWARD,
    REPLY_RD_SHARED,
)
from hpa2_tpu.analysis.table import (
    MSG_EVENTS,
    TransitionTable,
    build_table,
)

#: table state letters -> enum members
_CACHE_BY_LETTER = {
    "M": CacheState.MODIFIED,
    "E": CacheState.EXCLUSIVE,
    "S": CacheState.SHARED,
    "I": CacheState.INVALID,
    "O": CacheState.OWNED,
    "F": CacheState.FORWARD,
}
_HOME_BY_NAME = {
    "EM": DirState.EM,
    "S": DirState.S,
    "U": DirState.U,
    "SO": DirState.SO,
}
#: REPLY_RD flag symbols (Emit.sharers / REPLY_RD guard-case suffixes)
_RD_FLAGS = {
    "excl": REPLY_RD_EXCLUSIVE,
    "shared": REPLY_RD_SHARED,
    "fwd": REPLY_RD_FORWARD,
    "fwdf": REPLY_RD_FORWARD,
}


class TableCompileError(ValueError):
    """The table violates an invariant the lowering depends on."""


@dataclasses.dataclass(frozen=True)
class ProtocolPlanes:
    """The compiled protocol: int constants + state-set masks.

    Hashable (all-tuple fields) so it can ride jit-cache keys.  State
    ints are ``CacheState``/``DirState`` values; absent states are -1.
    """

    protocol: str
    cache_state_names: Tuple[str, ...]
    home_state_names: Tuple[str, ...]

    # ---- int constants ----
    M: int
    E: int
    S: int
    I: int  # noqa: E741 — the canonical MESI letter
    EM: int
    DS: int
    DU: int
    SO: int  # -1 unless the protocol has the shared-owned dir state
    O: int   # noqa: E741 — -1 unless MOESI
    F: int   # -1 unless MESIF

    # ---- cache-side state-set masks (sorted int tuples) ----
    inv_states: Tuple[int, ...]          # INV match -> INVALID
    wbint_resp_states: Tuple[int, ...]   # answer WRITEBACK_INT w/ FLUSH
    wbint_next_state: int                # responder's next state
    wbint_home_flush_states: Tuple[int, ...]  # responders that copy home
    fwd_count_states: Tuple[int, ...]    # cache-to-cache only (n_forwards)
    wbinv_resp_states: Tuple[int, ...]   # answer WRITEBACK_INV
    notify_pairs: Tuple[Tuple[int, int], ...]  # survivor promote map
    reply_rd_fill: Tuple[Tuple[int, int], ...]  # (flag, fill state)
    flush_fill_state: int                # FLUSH second-receiver fill
    read_hit_states: Tuple[int, ...]     # INSTR_R hit (no traffic)
    silent_write_states: Tuple[int, ...]  # INSTR_W hit, no traffic
    upgrade_write_states: Tuple[int, ...]  # INSTR_W hit -> UPGRADE
    dirty_evict_states: Tuple[int, ...]  # victim emits EVICT_MODIFIED

    # ---- home-side reply-kind constants ----
    rr_u_flag: int    # READ_REQUEST in U: REPLY_RD flag
    rr_s_flag: int    # READ_REQUEST served from dir S memory: flag
    nack_rd_flag: int  # NACK read re-serve: REPLY_RD flag

    @property
    def n_cache_states(self) -> int:
        """Size of the cache-state universe for state_in collapsing."""
        return len(self.cache_state_names)

    @property
    def has_so(self) -> bool:
        return self.SO >= 0

    @property
    def has_fwd(self) -> bool:
        return self.F >= 0

    @property
    def has_owner_plane(self) -> bool:
        """Does the home track an owner/forwarder pointer?"""
        return self.has_so or self.has_fwd

    def digest(self) -> str:
        """Reproducibility digest over the lowered planes."""
        d = dataclasses.asdict(self)
        blob = json.dumps(d, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()


def state_in(x, states: Sequence[int], universe: int):
    """Membership test over cache-state ints as an OR-chain.

    Collapses the (universe-1)-subset to a single ``!=`` against the
    missing member — the MESI fast paths keep their historical
    ``state != INVALID`` form in every protocol.
    """
    members = sorted(set(int(s) for s in states))
    if not members:
        return x != x
    if len(members) >= universe:
        return x == x
    if len(members) == universe - 1:
        missing = next(s for s in range(universe) if s not in members)
        return x != missing
    r = x == members[0]
    for s in members[1:]:
        r = r | (x == s)
    return r


def generated_dispatch() -> Dict[MsgType, str]:
    """The canonical MsgType -> spec-handler-name map, derived from the
    table's event vocabulary.  ``SpecEngine._DISPATCH`` stays a literal
    (the lint rule pins that) and asserts equality against this at
    import, so the literal cannot drift from the table."""
    return {MsgType[name]: "_on_" + name.lower() for name in MSG_EVENTS}


def _states_of(rows, pred) -> Tuple[str, ...]:
    return tuple(sorted({r.state for r in rows if pred(r)}))


def compile_planes(table: TransitionTable) -> ProtocolPlanes:
    """Derive the planes from table rows (no hand-written state sets)."""
    cletters = table.cache_states
    ci = {s: int(_CACHE_BY_LETTER[s]) for s in cletters}
    hi = {s: int(_HOME_BY_NAME[s]) for s in table.home_states}
    sem = table.semantics

    def cs(letters) -> Tuple[int, ...]:
        return tuple(sorted(ci[s] for s in letters))

    crows = [r for r in table.rows if r.role == "cache"]
    hrows = [r for r in table.rows if r.role == "home"]

    def cell(state, event):
        return [r for r in crows if r.state == state and r.event == event]

    # INV: states whose match row actually transitions to INVALID
    # (the I/M drop rows are no-ops, not invalidations)
    inv_states = _states_of(
        crows, lambda r: r.event == "INV" and r.case == "match"
        and not r.drop and r.next_state == "I" and r.state != "I")

    # WRITEBACK_INT responders: any row of the cell emits FLUSH
    def emits_type(r, t):
        return any(e.type == t for e in r.emits)

    wbint_rows = [r for r in crows if r.event == "WRITEBACK_INT"
                  and emits_type(r, "FLUSH")]
    wbint_resp = tuple(sorted({r.state for r in wbint_rows}))
    nexts = {r.next_state for r in wbint_rows}
    if len(nexts) != 1:
        raise TableCompileError(
            f"WRITEBACK_INT responders disagree on the next state: "
            f"{sorted(nexts)} — the lowering needs one")
    wbint_next = ci[nexts.pop()]
    wbint_home_flush = tuple(sorted({
        r.state for r in wbint_rows
        if any(e.type == "FLUSH" and e.to == "home" for e in r.emits)}))
    fwd_count = tuple(s for s in wbint_resp if s not in wbint_home_flush)

    wbinv_resp = _states_of(
        crows, lambda r: r.event == "WRITEBACK_INV"
        and emits_type(r, "FLUSH_INVACK"))

    # survivor promote map (the notify event name depends on the
    # overloaded-notify semantics quirk)
    notify_event = ("EVICT_SHARED" if sem.overloaded_evict_shared_notify
                    else "UPGRADE_NOTIFY")
    notify_pairs = tuple(sorted(
        (ci[r.state], ci[r.next_state])
        for r in crows
        if r.event == notify_event and r.case == "match_from_home"
        and r.next_state != r.state))

    # REPLY_RD fill map from the I-state rows' flag-named cases
    fill = {}
    for r in cell("I", "REPLY_RD"):
        fill[_RD_FLAGS[r.case]] = ci[r.next_state]
    if not fill:
        raise TableCompileError("no REPLY_RD fill rows for INVALID")
    reply_rd_fill = tuple(sorted(fill.items()))

    flush_rows = cell("I", "FLUSH")
    if len(flush_rows) != 1:
        raise TableCompileError("expected exactly one I/FLUSH row")
    flush_fill = ci[flush_rows[0].next_state]

    read_hit = _states_of(
        crows, lambda r: r.event == "INSTR_R" and r.case == "hit")
    silent_write = _states_of(
        crows, lambda r: r.event == "INSTR_W" and r.case == "hit"
        and not r.emits)
    upgrade_write = _states_of(
        crows, lambda r: r.event == "INSTR_W" and r.case == "hit"
        and emits_type(r, "UPGRADE"))
    dirty_evict = _states_of(
        crows, lambda r: r.event == "INSTR_R" and r.case == "miss_victim"
        and emits_type(r, "EVICT_MODIFIED"))

    # home reply kinds
    def rd_flag(state, cases) -> int:
        for r in hrows:
            if r.state == state and r.event == "READ_REQUEST" \
                    and r.case in cases:
                for e in r.emits:
                    if e.type == "REPLY_RD":
                        return _RD_FLAGS[e.sharers]
        raise TableCompileError(
            f"no memory-served REPLY_RD row for home {state}")

    rr_u = rd_flag("U", ("any",))
    rr_s = rd_flag("S", ("any", "no_fwd"))
    nack_rd = rr_s
    for r in hrows:
        if r.event == "NACK" and r.case == "read_intervention":
            for e in r.emits:
                if e.type == "REPLY_RD":
                    nack_rd = _RD_FLAGS[e.sharers]
            break

    return ProtocolPlanes(
        protocol=table.protocol,
        cache_state_names=tuple(cletters),
        home_state_names=tuple(table.home_states),
        M=ci["M"], E=ci["E"], S=ci["S"], I=ci["I"],
        EM=hi["EM"], DS=hi["S"], DU=hi["U"],
        SO=hi.get("SO", -1),
        O=ci.get("O", -1),
        F=ci.get("F", -1),
        inv_states=cs(inv_states),
        wbint_resp_states=cs(wbint_resp),
        wbint_next_state=wbint_next,
        wbint_home_flush_states=cs(wbint_home_flush),
        fwd_count_states=cs(fwd_count),
        wbinv_resp_states=cs(wbinv_resp),
        notify_pairs=notify_pairs,
        reply_rd_fill=reply_rd_fill,
        flush_fill_state=flush_fill,
        read_hit_states=cs(read_hit),
        silent_write_states=cs(silent_write),
        upgrade_write_states=cs(upgrade_write),
        dirty_evict_states=cs(dirty_evict),
        rr_u_flag=rr_u,
        rr_s_flag=rr_s,
        nack_rd_flag=nack_rd,
    )


@functools.lru_cache(maxsize=None)
def planes_for(protocol: str, semantics: Semantics) -> ProtocolPlanes:
    """Build + statically check + lower one protocol's table (cached)."""
    table = build_table(semantics, protocol)
    from hpa2_tpu.analysis.checks import run_static_checks
    errors = [f for f in run_static_checks(table) if f.severity == "error"]
    if errors:
        raise TableCompileError(
            f"the {protocol} table fails its static checks:\n"
            + "\n".join(str(f) for f in errors))
    return compile_planes(table)
