"""Protocols-as-data: compile the declarative TransitionTable into the
dense int-indexed planes the kernels execute.

``planes_for(protocol, semantics)`` is the one entry point every
backend shares: it builds the protocol's table, runs the static checks
(completeness / determinism / no-silent-drop / state-product /
reply-guarantee) as a build-time gate, and lowers the rows into a
``ProtocolPlanes`` record of integer state constants and state-set
masks.  ``ops/step.py``'s masked transition logic, the Pallas kernel's
dispatch constants, and the spec engine's handler guards all read these
planes instead of hand-written MESI state constants — a new protocol is
a table edit, zero kernel work.

``directory.py`` holds the directory-format variants (full bitvector,
limited-pointer with overflow-to-broadcast, coarse-vector) applied at
the home's invalidation fan-out composition.
"""

from hpa2_tpu.protocols.compiler import (  # noqa: F401
    ProtocolPlanes,
    compile_planes,
    generated_dispatch,
    planes_for,
    state_in,
)
from hpa2_tpu.protocols.directory import (  # noqa: F401
    DIRECTORY_FORMATS,
    dir_mask_int,
    group_mask_words,
    parse_format,
)
