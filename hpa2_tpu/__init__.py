"""hpa2_tpu — a TPU-native directory-MESI DSM simulation framework.

A from-scratch rebuild of the capabilities of ruubhagat/HP-Assignment-2
(a DASH-style directory-based MESI cache-coherence simulator for a
distributed shared memory system, /root/reference/assignment.c) designed
TPU-first:

* ``hpa2_tpu.models``   — the protocol data model and the pure-Python
  reference-semantics engine (the executable spec / differential oracle).
* ``hpa2_tpu.ops``      — the JAX execution backend: a single jitted
  lockstep step function over struct-of-arrays state, vmapped over a
  batch of independent systems, run to quiescence under
  ``lax.while_loop``.
* ``hpa2_tpu.parallel`` — device-mesh sharding (``shard_map``/``pjit``)
  of the batch and node axes with XLA collectives for cross-shard
  message delivery.
* ``hpa2_tpu.utils``    — trace / dump I/O (byte-exact with the
  reference's ``core_<n>_output.txt`` format), synthetic trace
  generators, comparison helpers.
* ``hpa2_tpu.native``   — ctypes bindings to the C++/OpenMP native
  engine (``native/``), the free-running thread-per-node backend and
  ops/sec baseline.
"""

from hpa2_tpu.config import SystemConfig, Semantics

__all__ = ["SystemConfig", "Semantics"]
__version__ = "0.1.0"
