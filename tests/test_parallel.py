"""Multi-chip sharding: the node-sharded / grid engines must be
bit-identical to the single-chip JAX engine (same cycles, counters,
snapshots) — delivery order is preserved across the all_gather
(ops/step.py phase C; SURVEY.md §2.4).

Runs on the virtual 8-device CPU mesh from conftest.
"""

import jax
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.parallel import GridEngine, NodeShardedEngine, make_mesh
from hpa2_tpu.utils.trace import (
    gen_producer_consumer,
    gen_uniform_random,
    load_trace_dir,
)

ROBUST = Semantics().robust()


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _assert_equal(sharded, ref):
    assert sharded.cycle == ref.cycle
    assert sharded.instructions == ref.instructions
    assert sharded.messages == ref.messages
    assert sharded.snapshots() == ref.snapshots()
    assert sharded.final_dumps() == ref.final_dumps()


@pytest.mark.parametrize("node_shards", [2, 4, 8])
def test_node_sharded_matches_single_chip(node_shards):
    _require_devices(node_shards)
    cfg = SystemConfig(num_procs=8, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 40, seed=1)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=node_shards)
    ).run()
    _assert_equal(eng, ref)


def test_node_sharded_producer_consumer_16_nodes():
    _require_devices(8)
    cfg = SystemConfig(num_procs=16, semantics=ROBUST)
    traces = gen_producer_consumer(cfg, 24, seed=3)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=8)
    ).run()
    _assert_equal(eng, ref)


def test_node_sharded_fixture_traces(reference_tests_dir):
    """Deterministic suite (node-local traffic only) through the
    sharded engine reproduces the single-chip snapshots."""
    _require_devices(4)
    cfg = SystemConfig()
    traces = load_trace_dir(str(reference_tests_dir / "test_1"), cfg)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    ).run()
    _assert_equal(eng, ref)


def test_grid_matches_per_system():
    _require_devices(8)
    cfg = SystemConfig(num_procs=8, semantics=ROBUST)
    batch = [gen_uniform_random(cfg, 30, seed=s) for s in range(4)]
    grid = GridEngine(cfg, batch, mesh=make_mesh(node_shards=2)).run()
    for b, traces in enumerate(batch):
        ref = JaxEngine(cfg, traces).run()
        assert grid.system_snapshots(b) == ref.snapshots()
