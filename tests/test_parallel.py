"""Multi-chip sharding: the node-sharded / grid engines must be
bit-identical to the single-chip JAX engine (same cycles, counters,
snapshots) — delivery order is preserved across the targeted
cross-shard exchange (ops/step.py phase C via ops/exchange.py;
SURVEY.md §2.4).

Runs on the virtual 8-device CPU mesh from conftest.
"""

import jax
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.parallel import GridEngine, NodeShardedEngine, make_mesh
from hpa2_tpu.utils.trace import (
    gen_producer_consumer,
    gen_uniform_random,
    load_trace_dir,
)

ROBUST = Semantics().robust()


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _assert_equal(sharded, ref):
    assert sharded.cycle == ref.cycle
    assert sharded.instructions == ref.instructions
    assert sharded.messages == ref.messages
    assert sharded.snapshots() == ref.snapshots()
    assert sharded.final_dumps() == ref.final_dumps()


@pytest.mark.parametrize("node_shards", [2, 4, 8])
def test_node_sharded_matches_single_chip(node_shards):
    _require_devices(node_shards)
    cfg = SystemConfig(num_procs=8, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 40, seed=1)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=node_shards)
    ).run()
    _assert_equal(eng, ref)


def test_node_sharded_producer_consumer_16_nodes():
    _require_devices(8)
    cfg = SystemConfig(num_procs=16, semantics=ROBUST)
    traces = gen_producer_consumer(cfg, 24, seed=3)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=8)
    ).run()
    _assert_equal(eng, ref)


def test_node_sharded_fixture_traces(reference_tests_dir):
    """Deterministic suite (node-local traffic only) through the
    sharded engine reproduces the single-chip snapshots."""
    _require_devices(4)
    cfg = SystemConfig()
    traces = load_trace_dir(str(reference_tests_dir / "test_1"), cfg)
    ref = JaxEngine(cfg, traces).run()
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    ).run()
    _assert_equal(eng, ref)


def test_grid_matches_per_system():
    _require_devices(8)
    cfg = SystemConfig(num_procs=8, semantics=ROBUST)
    batch = [gen_uniform_random(cfg, 30, seed=s) for s in range(4)]
    grid = GridEngine(cfg, batch, mesh=make_mesh(node_shards=2)).run()
    for b, traces in enumerate(batch):
        ref = JaxEngine(cfg, traces).run()
        assert grid.system_snapshots(b) == ref.snapshots()


def test_data_sharding_divides_work_8_shards():
    """Throughput-scaling evidence on the virtual mesh (VERDICT round-4
    item 7): with ``data_shards=8`` over a batch-64 ensemble, each
    device owns exactly 1/8 of the systems (its addressable shard),
    the per-device work partition is balanced (>= 6x effective
    parallel work = total instructions / busiest device), and
    wall-cycles match the unsharded run — i.e. sharding divides the
    work without inflating the critical path.  Wall-clock is NOT
    asserted: the 8 virtual devices share this host's physical cores;
    on real chips the same partition rides one device each.
    """
    import numpy as np

    _require_devices(8)
    cfg = SystemConfig(num_procs=8, msg_buffer_size=16, semantics=ROBUST)
    batch = [gen_uniform_random(cfg, 40, seed=100 + s) for s in range(64)]

    sharded = GridEngine(
        cfg, batch, mesh=make_mesh(node_shards=1, data_shards=8)
    ).run()
    single = GridEngine(
        cfg, batch, mesh=make_mesh(node_shards=1, data_shards=1)
    ).run()

    n_instr = sharded.state.n_instr            # [64] sharded over data
    shards = n_instr.addressable_shards
    assert len(shards) == 8
    per_dev = []
    seen_devices = set()
    for sh in shards:
        assert sh.data.shape == (8,), "each device must own batch/8"
        seen_devices.add(sh.device)
        per_dev.append(int(np.sum(np.asarray(sh.data))))
    assert len(seen_devices) == 8, "shards must land on distinct devices"

    total = int(np.sum(np.asarray(n_instr)))
    assert sum(per_dev) == total, "shards must partition the work"
    assert total / max(per_dev) >= 6.0, (
        f"effective parallel work {total / max(per_dev):.2f}x < 6x: "
        f"per-device {per_dev}"
    )

    # the critical path (lockstep wall-cycles per system) is unchanged
    # by sharding -- bit-identical engines
    assert np.array_equal(
        np.asarray(sharded.state.cycle), np.asarray(single.state.cycle)
    )
    assert sharded.instructions == single.instructions


def test_unified_data_shards_knob_both_backends():
    """One ``data_shards=`` knob, same name and semantics, on both
    ensemble backends: the XLA batch engine (shard_map(vmap(step)))
    and the Pallas fast path (DataShardedPallasEngine).  Same
    workload through both must land sharded on the same 8 devices and
    agree on the final node dumps, cross-backend."""
    import numpy as np

    from hpa2_tpu.ops.engine import BatchJaxEngine
    from hpa2_tpu.parallel import DataShardedPallasEngine
    from hpa2_tpu.utils.trace import traces_to_arrays

    _require_devices(8)
    cfg = SystemConfig(num_procs=8, msg_buffer_size=16, semantics=ROBUST)
    batch = [gen_uniform_random(cfg, 24, seed=40 + s) for s in range(16)]

    xla = BatchJaxEngine(cfg, batch, data_shards=8).run()
    plz = DataShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, batch), data_shards=8,
        block=2, cycles_per_call=64, snapshots=False,
    ).run()

    assert xla.data_shards == plz.data_shards == 8
    # the knob actually sharded both backends' carried state: batch/8
    # systems per device, on the same 8 distinct devices
    xs = xla.state.n_instr.addressable_shards         # [16] over data
    ps = plz.state["scalars"].addressable_shards      # [..., 16] lanes
    assert len(xs) == len(ps) == 8
    assert {s.device for s in xs} == {s.device for s in ps}
    assert all(s.data.shape == (2,) for s in xs)
    assert all(s.data.shape[-1] == 2 for s in ps)

    assert plz.instructions == xla.instructions
    for s in (0, 5, 15):
        assert [d.__dict__ for d in plz.system_final_dumps(s)] == [
            d.__dict__ for d in xla.system_final_dumps(s)
        ], f"backends disagree on system {s} under the shared knob"
    # schedule agreement on the ensemble wall-clock (the XLA batch
    # engine ticks every system's counter until the whole batch
    # quiesces; Pallas lanes freeze theirs at local quiescence — so
    # only the max is comparable)
    from hpa2_tpu.ops.pallas_engine import _SC_CYCLE

    assert int(np.max(np.asarray(xla.state.cycle))) == int(
        np.max(np.asarray(plz.state["scalars"])[_SC_CYCLE])
    )
