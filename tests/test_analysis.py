"""Static analysis suite tests: table checks, lint, mutation self-test,
and the analysis CLI.
"""

import subprocess
import sys

import pytest

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.table import CASE_UNIVERSE, build_table
from hpa2_tpu.analysis.checks import run_static_checks
from hpa2_tpu.analysis.lint import run_lint
from hpa2_tpu.analysis.mutate import MUTATIONS, run_all_mutations

SEMS = {
    "default": Semantics(),
    "robust": Semantics().robust(),
    "head": Semantics().head_quirks(),
}


@pytest.mark.parametrize("name", sorted(SEMS))
def test_shipped_table_has_no_errors(name):
    findings = run_static_checks(build_table(SEMS[name]))
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, "\n".join(str(f) for f in errors)


def test_drop_policy_warnings_are_the_only_warnings():
    """Under the drop policy the reply chain visibly ends in documented
    hangs — warnings, never errors; under nack there is nothing to
    warn about."""
    warn = [f for f in run_static_checks(build_table(SEMS["default"]))
            if f.severity == "warning"]
    assert warn and all(f.check == "reply-guarantee" for f in warn)
    assert not [f for f in run_static_checks(build_table(SEMS["robust"]))
                if f.severity == "warning"]


def test_case_universe_is_semantics_invariant():
    """Policy knobs change row *content*, never which guard-cases
    exist — all variants tile the same universe."""
    sizes = {
        name: sum(
            len(cases)
            for per_state in CASE_UNIVERSE.values()
            for cases in per_state.values()
        )
        for name in SEMS
    }
    assert len(set(sizes.values())) == 1
    for name, sem in SEMS.items():
        t = build_table(sem)
        covered = {r.key for r in t.rows}
        for (role, event), per_state in CASE_UNIVERSE.items():
            for state, cases in per_state.items():
                for case in cases:
                    assert (role, state, event, case) in covered \
                        or t.is_unreachable(role, state, event, case), \
                        (name, role, state, event, case)


def test_lint_clean_on_shipped_engine_code():
    findings = run_lint(".")
    assert not findings, "\n".join(str(f) for f in findings)


def test_lint_catches_seeded_pitfalls(tmp_path):
    bad = (
        "import time, random\n"
        "import jax.numpy as jnp\n"
        "def step(st, config):\n"
        "    if st.waiting[0]:\n"
        "        pass\n"
        "    t = time.time()\n"
        "    x = random.randint(0, 3)\n"
        "    y = jnp.zeros(4, dtype=jnp.int64)\n"
        "    z = jnp.arange(4).astype(int)\n"
    )
    (tmp_path / "hpa2_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "hpa2_tpu" / "models").mkdir(parents=True)
    (tmp_path / "hpa2_tpu" / "ops" / "bad.py").write_text(bad)
    rules = {f.rule for f in run_lint(str(tmp_path))}
    assert {"traced-branch", "nondeterminism", "dtype-drift"} <= rules


def test_lint_dead_handler_detection(tmp_path):
    """A handler missing from _DISPATCH and an unmapped MsgType must
    both be flagged."""
    import re

    src = open("hpa2_tpu/models/spec_engine.py").read()
    mutated, n = re.subn(r"MsgType\.NACK: \"_on_nack\",\n", "", src)
    assert n == 1
    (tmp_path / "hpa2_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "hpa2_tpu" / "models").mkdir(parents=True)
    (tmp_path / "hpa2_tpu" / "models" / "spec_engine.py").write_text(mutated)
    msgs = [f.message for f in run_lint(str(tmp_path))
            if f.rule == "dead-handler"]
    assert any("_on_nack" in m for m in msgs)
    assert any("MsgType.NACK" in m for m in msgs)


def test_every_seeded_mutation_is_caught():
    results = run_all_mutations()
    missed = [r.name for r in results if not r.caught]
    assert not missed, f"analyzer missed mutations: {missed}"
    assert len(results) == len(MUTATIONS) >= 10


def test_mutations_exercise_both_catchers():
    """The suite must prove both halves of the analyzer: some bugs are
    only structural (static), some only behavioral (spec diff)."""
    by = {r.caught_by for r in run_all_mutations()}
    assert by == {"static", "spec-diff"}


@pytest.mark.parametrize("argv,expect_rc", [
    (["check"], 0),
    (["lint"], 0),
    (["mutation-test"], 0),
])
def test_cli_subcommands(argv, expect_rc):
    proc = subprocess.run(
        [sys.executable, "-m", "hpa2_tpu.analysis"] + argv,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == expect_rc, proc.stdout + proc.stderr
