"""Fault injection, stall watchdog, and crash-resume.

The acceptance bar for the fault layer is *masking*: with drop/dup/
reorder faults enabled, the link-layer retry must hide every fault
from the protocol, so final dumps are byte-identical to a fault-free
run of the same workload — on the spec engine and the JAX engine
alike.  A fully severed link (drop=1.0 on one edge) is the one
unmaskable fault; there the watchdog must convert a silent livelock
into a structured ``StallDiagnostic`` well before ``max_cycles``.
"""

import dataclasses
import os

import pytest

from hpa2_tpu.config import FaultModel, Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine, StallDiagnostic
from hpa2_tpu.utils.checkpoint import (
    load_spec_state,
    save_spec_state,
)
from hpa2_tpu.utils.invariants import check_invariants
from hpa2_tpu.utils.trace import (
    gen_eviction_pingpong,
    gen_producer_consumer,
    gen_uniform_random,
)

ROBUST = Semantics().robust()

# the acceptance-criteria fault mix from the issue
ACCEPT = dict(drop=0.2, duplicate=0.1, reorder=0.2, seed=7)

SUITES = [gen_uniform_random, gen_producer_consumer, gen_eviction_pingpong]


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


def _golden(cfg, traces):
    eng = SpecEngine(cfg, traces)
    eng.run()
    return _dicts(eng.final_dumps())


# -- differential masking ---------------------------------------------


@pytest.mark.parametrize("gen", SUITES, ids=lambda g: g.__name__)
@pytest.mark.parametrize("fault", [
    dict(),                                      # rate 0 == golden path
    dict(drop=0.1, seed=3),
    dict(duplicate=0.3, reorder=0.3, seed=11),
    ACCEPT,
], ids=["off", "drop", "dup-reorder", "accept-mix"])
def test_spec_faults_masked(gen, fault):
    cfg0 = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen(cfg0, 24, seed=5)
    golden = _golden(cfg0, traces)

    cfg = dataclasses.replace(cfg0, fault=FaultModel(**fault))
    eng = SpecEngine(cfg, traces)
    eng.run()
    assert _dicts(eng.final_dumps()) == golden
    assert check_invariants(eng.final_dumps(), cfg) == []
    if FaultModel(**fault).enabled and fault.get("drop"):
        # faults actually happened and were masked, not avoided
        assert eng.counters["fault_retransmissions"] > 0


def test_spec_faults_masked_across_seeds():
    cfg0 = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg0, 24, seed=9)
    golden = _golden(cfg0, traces)
    for seed in (0, 1, 2, 3, 4):
        cfg = dataclasses.replace(
            cfg0, fault=FaultModel(drop=0.25, duplicate=0.1, seed=seed)
        )
        eng = SpecEngine(cfg, traces)
        eng.run()
        assert _dicts(eng.final_dumps()) == golden, f"seed {seed}"


def test_jax_faults_masked():
    from hpa2_tpu.ops.engine import JaxEngine

    cfg0 = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg0, 24, seed=5)
    golden = _golden(cfg0, traces)

    cfg = dataclasses.replace(cfg0, fault=FaultModel(**ACCEPT))
    eng = JaxEngine(cfg, traces)
    eng.run()
    assert _dicts(eng.final_dumps()) == golden
    s = eng.stats()
    assert s["fault_retransmissions"] > 0
    # the schedule itself is untouched: same cycle count as fault-free
    ref = SpecEngine(cfg0, traces)
    ref.run()
    assert eng.cycle == ref.cycle


def test_fault_counters_absent_when_fault_free():
    from hpa2_tpu.ops.engine import JaxEngine

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 16, seed=0)
    eng = JaxEngine(cfg, traces)
    eng.run()
    assert not any(k.startswith("fault_") for k in eng.stats())
    spec = SpecEngine(cfg, traces)
    spec.run()
    assert not any(k.startswith("fault_") for k in spec.counters)


# -- watchdog / livelock ----------------------------------------------

SEVERED = FaultModel(drop=1.0, edge_sender=1, edge_receiver=0, seed=1)


def _check_diag(e: StallDiagnostic, n: int):
    assert e.cycle < 100_000  # long before max_cycles
    assert len(e.mailbox_depths) == n
    assert e.recent_msgs  # flight recorder captured deliveries
    text = str(e)
    assert "watchdog" in text
    assert "mailbox depths" in text


def test_spec_watchdog_on_severed_link():
    cfg = SystemConfig(
        num_procs=4, semantics=ROBUST, fault=SEVERED
    )
    traces = gen_uniform_random(cfg, 16, seed=3)
    eng = SpecEngine(cfg, traces)
    with pytest.raises(StallDiagnostic) as ei:
        eng.run(max_cycles=100_000, watchdog_cycles=50)
    _check_diag(ei.value, 4)


def test_jax_watchdog_on_severed_link():
    from hpa2_tpu.ops.engine import JaxEngine

    cfg = SystemConfig(
        num_procs=4, semantics=ROBUST, fault=SEVERED
    )
    traces = gen_uniform_random(cfg, 16, seed=3)
    eng = JaxEngine(cfg, traces, watchdog_cycles=50)
    with pytest.raises(StallDiagnostic) as ei:
        eng.run()
    _check_diag(ei.value, 4)


def test_watchdog_quiet_on_clean_run():
    # a healthy run must never trip a tight watchdog: every cycle
    # with in-flight work either retires or drains something
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 24, seed=5)
    eng = SpecEngine(cfg, traces)
    eng.run(watchdog_cycles=10)
    assert eng.quiescent()


# -- invariants under faults ------------------------------------------


def test_em_reverse_invariant_catches_dropped_ownership_reply():
    cfg = SystemConfig(semantics=ROBUST)
    traces = gen_uniform_random(cfg, 24, seed=5)
    eng = SpecEngine(cfg, traces)
    eng.run()
    dumps = eng.final_dumps()
    # fabricate the dropped-REPLY_WR signature: home directory says
    # EM{owner}, owner's cache still holds the INVALID placeholder
    home, blk, owner = 0, 2, 1
    addr = cfg.make_addr(home, blk)
    dumps[home].dir_state[blk] = 0  # DirState.EM
    dumps[home].dir_sharers[blk] = 1 << owner
    slot = cfg.cache_index_of(addr)
    dumps[owner].cache_addr[slot] = addr
    dumps[owner].cache_state[slot] = 3  # CacheState.INVALID placeholder
    assert any(
        "dropped ownership reply" in msg
        for msg in check_invariants(dumps, cfg)
    )


def test_debug_invariants_clean_under_faults():
    cfg = SystemConfig(
        num_procs=4, semantics=ROBUST, fault=FaultModel(**ACCEPT)
    )
    traces = gen_uniform_random(cfg, 16, seed=5)
    eng = SpecEngine(cfg, traces, debug_invariants=True)
    eng.run()  # per-step mid-flight checks raise on any violation
    assert eng.quiescent()


def test_stall_diagnostic_runs_invariant_check():
    cfg = SystemConfig(num_procs=4, semantics=ROBUST, fault=SEVERED)
    traces = gen_uniform_random(cfg, 16, seed=3)
    eng = SpecEngine(cfg, traces)
    with pytest.raises(StallDiagnostic) as ei:
        eng.run(watchdog_cycles=50)
    # the diagnostic carries the mid-flight invariant sweep (empty
    # here: a severed link starves the protocol but corrupts nothing)
    assert ei.value.invariant_violations == []


# -- crash + resume ---------------------------------------------------


@pytest.mark.parametrize("crash_at", [1, 17, 60])
def test_spec_crash_resume_matches_uninterrupted(tmp_path, crash_at):
    cfg = SystemConfig(
        num_procs=4, semantics=ROBUST, fault=FaultModel(**ACCEPT)
    )
    traces = gen_uniform_random(cfg, 24, seed=5)

    straight = SpecEngine(cfg, traces)
    straight.run()

    eng = SpecEngine(cfg, traces)
    for _ in range(crash_at):
        eng.step()
    path = os.path.join(tmp_path, "spec_ckpt.json")
    save_spec_state(path, eng)
    del eng  # the "crash"

    resumed = load_spec_state(path)
    assert resumed.cycle == crash_at
    resumed.run()
    assert _dicts(resumed.final_dumps()) == _dicts(straight.final_dumps())
    assert resumed.counters == straight.counters
    assert resumed.cycle == straight.cycle
    assert resumed.issue_log == straight.issue_log


def test_spec_checkpoint_rejects_garbage(tmp_path):
    p = os.path.join(tmp_path, "bad.json")
    with open(p, "w") as f:
        f.write('{"magic": "nope"}')
    with pytest.raises(ValueError):
        load_spec_state(p)


# -- CLI surface ------------------------------------------------------


def _write_trace_dir(tmp_path, cfg, traces):
    td = os.path.join(tmp_path, "traces")
    os.makedirs(td, exist_ok=True)
    for i, t in enumerate(traces):
        with open(os.path.join(td, f"core_{i}.txt"), "w") as f:
            for ins in t:
                f.write(
                    f"RD 0x{ins.address:02X}\n" if ins.op == "R"
                    else f"WR 0x{ins.address:02X} {ins.value}\n"
                )
    return td


def test_cli_fault_flags_masked(tmp_path):
    from hpa2_tpu.cli import main

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 16, seed=5)
    td = _write_trace_dir(str(tmp_path), cfg, traces)
    common = [
        "run", td, "--backend", "spec", "--robust", "--final-dump",
        "--max-instr", "16",
    ]
    golden = os.path.join(tmp_path, "golden")
    faulted = os.path.join(tmp_path, "faulted")
    assert main(common + ["--out", golden]) == 0
    assert main(common + [
        "--out", faulted,
        "--fault-drop", "0.2", "--fault-dup", "0.1",
        "--fault-reorder", "0.2", "--fault-seed", "7",
    ]) == 0
    for i in range(4):
        name = f"core_{i}_output.txt"
        with open(os.path.join(golden, name)) as g, \
                open(os.path.join(faulted, name)) as f:
            assert f.read() == g.read()


def test_cli_crash_resume(tmp_path):
    from hpa2_tpu.cli import main

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 16, seed=5)
    td = _write_trace_dir(str(tmp_path), cfg, traces)
    common = [
        "run", td, "--backend", "spec", "--robust", "--final-dump",
        "--max-instr", "16", "--fault-drop", "0.2", "--fault-seed", "7",
    ]
    golden = os.path.join(tmp_path, "golden")
    assert main(common + ["--out", golden]) == 0
    ck = os.path.join(tmp_path, "ck.json")
    assert main(common + [
        "--crash-at", "20", "--crash-checkpoint", ck,
    ]) == 0
    resumed = os.path.join(tmp_path, "resumed")
    assert main(common + ["--resume", ck, "--out", resumed]) == 0
    for i in range(4):
        name = f"core_{i}_output.txt"
        with open(os.path.join(golden, name)) as g, \
                open(os.path.join(resumed, name)) as f:
            assert f.read() == g.read()


def test_cli_rejects_fault_on_unsupported_backends(tmp_path):
    from hpa2_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["run", "x", "--backend", "pallas", "--fault-drop", "0.1"])
    with pytest.raises(SystemExit):
        main(["bench", "--backend", "omp", "--fault-drop", "0.1"])
    # jax + --node-shards now supports faults (the link-layer PRNG
    # folds the shard index in); pallas still has no fault model
    with pytest.raises(SystemExit):
        main(["run", "x", "--backend", "pallas", "--fault-drop", "0.1",
              "--node-shards", "2"])


# -- data-sharded ensembles -------------------------------------------
#
# Data sharding (node_shards=1) keeps whole systems per device, so the
# per-system link-layer PRNG stream — and therefore every injected
# fault — is identical however the ensemble is partitioned.  Masking
# and the watchdog diagnostic must not notice the mesh.

_DIAG_FIELDS = (
    "reason", "cycle", "mailbox_depths", "waiting", "blocked",
    "line_states", "recent_msgs", "invariant_violations", "counters",
)


@pytest.mark.virtual_mesh
def test_batch_faults_masked_data_sharded():
    import jax
    import numpy as np

    from hpa2_tpu.ops.engine import BatchJaxEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg0 = SystemConfig(num_procs=4, semantics=ROBUST)
    batch = [gen_uniform_random(cfg0, 20, seed=20 + s) for s in range(16)]
    cfg = dataclasses.replace(cfg0, fault=FaultModel(**ACCEPT))

    one = BatchJaxEngine(cfg, batch).run()
    shd = BatchJaxEngine(cfg, batch, data_shards=8).run()

    # the sharded ensemble is bit-identical to the unsharded one
    for a, b in zip(
        jax.tree_util.tree_leaves(one.state),
        jax.tree_util.tree_leaves(shd.state),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    retrans = shd.stats()["fault_retransmissions"]
    assert retrans == one.stats()["fault_retransmissions"]
    assert retrans > 0  # faults happened and were masked, not avoided

    # ... and masked down to golden per-system dumps
    for s in (0, 7, 15):
        assert _dicts(shd.system_final_dumps(s)) == _golden(
            cfg0, batch[s]
        )


@pytest.mark.virtual_mesh
def test_batch_watchdog_diag_identical_across_sharding():
    import jax

    from hpa2_tpu.ops.engine import BatchJaxEngine, JaxEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = SystemConfig(num_procs=4, semantics=ROBUST, fault=SEVERED)
    traces = gen_uniform_random(cfg, 16, seed=3)
    batch = [traces for _ in range(8)]

    diags = []
    for shards in (1, 8):
        eng = BatchJaxEngine(
            cfg, batch, max_cycles=100_000,
            watchdog_cycles=50, data_shards=shards,
        )
        with pytest.raises(StallDiagnostic) as ei:
            eng.run()
        diags.append(ei.value)
    d1, d8 = diags
    _check_diag(d8, 4)
    for f in _DIAG_FIELDS:
        assert getattr(d1, f) == getattr(d8, f), (
            f"diagnostic field {f!r} differs between data_shards=1 "
            "and data_shards=8"
        )

    # and both match the single-system engine on everything but the
    # reason string (which names the stalled system in the batch)
    ref = JaxEngine(cfg, traces, watchdog_cycles=50)
    with pytest.raises(StallDiagnostic) as ei:
        ref.run()
    for f in _DIAG_FIELDS[1:]:
        assert getattr(ei.value, f) == getattr(d8, f)


# -- node-sharded faults ----------------------------------------------
#
# Node sharding splits ONE faulty system across devices, so the
# link-layer PRNG folds the shard index into its mask keys: each shard
# draws an independent stream and the injected faults differ from the
# unsharded run.  The invariant that survives any partition is
# *masking* — the retry layer must hide every fault, so final dumps
# are byte-identical to the fault-free golden whatever the mesh.


@pytest.mark.virtual_mesh
@pytest.mark.parametrize("node_shards", [2, 4])
def test_node_sharded_faults_masked(node_shards):
    import jax

    from hpa2_tpu.parallel.sharding import NodeShardedEngine, make_mesh

    if len(jax.devices()) < node_shards:
        pytest.skip(f"needs {node_shards} devices")
    cfg0 = SystemConfig(num_procs=8, semantics=ROBUST)
    traces = gen_uniform_random(cfg0, 20, seed=6)
    golden = _golden(cfg0, traces)

    cfg = dataclasses.replace(cfg0, fault=FaultModel(**ACCEPT))
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=node_shards)
    ).run()
    assert _dicts(eng.final_dumps()) == golden
    assert check_invariants(eng.final_dumps(), cfg) == []
    # ... and therefore identical to the unsharded faulty run's dumps
    # (each is masked down to the same golden)
    from hpa2_tpu.ops.engine import JaxEngine

    jx = JaxEngine(cfg, traces).run()
    assert _dicts(eng.final_dumps()) == _dicts(jx.final_dumps())
    assert jx.stats()["fault_retransmissions"] > 0
    # faults actually crossed the targeted exchange and were masked,
    # not avoided
    assert eng.stats()["fault_retransmissions"] > 0
    # schedule untouched: same wall-cycles as the fault-free run
    ref = SpecEngine(cfg0, traces)
    ref.run()
    assert eng.cycle == ref.cycle
