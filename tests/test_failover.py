"""Fault-tolerant serving (ISSUE 16): shard-failure injection,
checkpointed live migration, and wire retry/timeout/backoff.

The contract under test (README "Fault tolerance"):

1. **Recovery is invisible in the output** — a seeded kill / hang /
   poison mid-run must finish with final dumps byte-identical to an
   unfailed run of the same feed, whatever backend or shard count the
   supervisor migrates onto (the primary's window schedule is carried
   across the migration, so the replay is the *same* legal schedule).
2. **The failure plan is pure data** — parse/spec round-trip, seeded
   backoff jitter is a pure function of (seed, attempt), and the
   injector fires each event exactly once at its interval barrier.
3. **The wire survives its transport** — a dead server raises
   :class:`ConnectionLost` instead of hanging, a mid-frame sever is
   ridden out by reconnect + session resume, and the resent SUBMIT
   draws the *original* ACK seq flagged ``dup`` (idempotence).
4. **Degradation is loud and accounted** — past ``shed_threshold``,
   batch-class jobs draw a structured shed-NACK and the count surfaces
   in the occupancy stats; checkpoint metadata (schema v2) carries the
   recovery counters, zero-backfilled when loading v1 files.
"""

import socket
import threading

import numpy as np
import pytest

from hpa2_tpu.config import (
    FailureEvent,
    FailurePlan,
    Semantics,
    SystemConfig,
)
from hpa2_tpu.service import (
    AdmissionLedger,
    AdmissionReject,
    AdmissionShed,
    ConnectionLost,
    FailureInjector,
    WireClient,
    WireJobSource,
    WireNack,
    backoff_delay,
)
from hpa2_tpu.serving import (
    ListJobSource,
    job_to_record,
    serve,
    supervised_serve,
    synthetic_jobs,
)

ROBUST = Semantics().robust()


@pytest.fixture(scope="module")
def cfg():
    return SystemConfig(num_procs=4, semantics=ROBUST)


@pytest.fixture(scope="module")
def jobs(cfg):
    return synthetic_jobs(cfg, 8, 24, seed=7, spread=3.0)


def _require_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _dump_map(results):
    return {r.job_id: tuple(repr(d) for d in r.dumps) for r in results}


def _recovery(stats):
    return stats.occupancy.get("recovery", {})


# -- the failure plan is pure data ------------------------------------------


def test_failure_plan_parse_spec_round_trip():
    plan = FailurePlan.parse("kill@3; hang@5:1 ;poison@2:7", seed=4)
    assert [e.kind for e in plan.events] == ["kill", "hang", "poison"]
    assert [(e.at, e.target) for e in plan.events] == [
        (3, 0), (5, 1), (2, 7)]
    assert plan.seed == 4
    assert plan.enabled
    assert FailurePlan.parse(plan.spec(), seed=4) == plan
    assert plan.of_kind("hang") == (FailureEvent("hang", 5, 1),)
    assert not FailurePlan.parse("").enabled


def test_failure_plan_rejects_bad_events():
    with pytest.raises(ValueError, match="unknown failure kind"):
        FailurePlan.parse("frob@1")
    with pytest.raises(ValueError, match="kind@at"):
        FailurePlan.parse("kill")
    with pytest.raises(ValueError):
        FailurePlan.parse("kill@x")
    with pytest.raises(ValueError, match=">= 0"):
        FailureEvent("kill", -1)


def test_backoff_is_seeded_capped_and_deterministic():
    a = [backoff_delay(i, base_s=0.05, cap_s=2.0, seed=9)
         for i in range(12)]
    assert a == [backoff_delay(i, base_s=0.05, cap_s=2.0, seed=9)
                 for i in range(12)]
    # jitter keeps every delay inside [envelope/2, envelope]
    for i, d in enumerate(a):
        env = min(2.0, 0.05 * 2.0 ** i)
        assert env / 2 <= d <= env
    assert all(d <= 2.0 for d in a)
    b = [backoff_delay(i, base_s=0.05, cap_s=2.0, seed=10)
         for i in range(12)]
    assert a != b  # the seed really feeds the jitter


def test_injector_fires_each_event_once():
    plan = FailurePlan.parse("kill@2")
    inj = FailureInjector(plan)
    inj.hook(0, None)
    inj.hook(1, None)
    from hpa2_tpu.service import InjectedFailure

    with pytest.raises(InjectedFailure) as ei:
        inj.hook(2, None)
    assert ei.value.event.kind == "kill"
    assert ei.value.interval == 2
    # once fired, the event never re-fires (the recovered run passes
    # the same barrier index again)
    inj.hook(2, None)
    inj.hook(3, None)
    assert not inj.pending


# -- checkpointed recovery: byte-identical dumps ----------------------------

_SWEEP = [
    pytest.param("jax", dict(max_trace_len=64, interval=8), id="jax"),
    pytest.param("pallas", dict(window=8, block=4), id="pallas"),
    pytest.param(
        "pallas-sharded",
        dict(window=8, block=4, data_shards=2),
        marks=pytest.mark.virtual_mesh, id="data_shards2"),
    pytest.param(
        "pallas-node-sharded",
        dict(window=8, block=4, node_shards=2),
        marks=pytest.mark.virtual_mesh, id="node_shards2"),
]


@pytest.mark.parametrize("backend,kw", _SWEEP)
def test_kill_recovers_byte_identical(cfg, jobs, tmp_path, backend, kw):
    """Kill the backend at interval barrier 3: the supervisor migrates
    the in-flight jobs onto the default target rotation and the final
    dumps match the unfailed run byte for byte."""
    if kw.get("data_shards", 1) > 1 or kw.get("node_shards", 1) > 1:
        _require_devices(2)
    base, _ = serve(cfg, ListJobSource(jobs), backend=backend,
                    resident=4, **kw)
    want = _dump_map(base)
    res, stats = supervised_serve(
        cfg, ListJobSource(jobs), plan=FailurePlan.parse("kill@3", seed=1),
        checkpoint_dir=str(tmp_path), backend=backend, resident=4, **kw)
    rec = _recovery(stats)
    assert _dump_map(res) == want
    assert rec["failures_detected"] == 1
    assert rec["migrations"] >= 1
    assert rec["evacuations"] >= 1
    assert rec["checkpoints"] >= 1
    assert stats.jobs_completed == len(jobs)


def test_jax_kill_resumes_lanes_mid_state(cfg, jobs, tmp_path):
    """jax -> jax migration goes through the schema-v2 npz checkpoint:
    live rows re-admit mid-state (not replayed from instruction 0) and
    still finish byte-identical to the unfailed run."""
    kw = dict(backend="jax", resident=4, max_trace_len=64, interval=8)
    base, _ = serve(cfg, ListJobSource(jobs), **kw)
    res, stats = supervised_serve(
        cfg, ListJobSource(jobs), plan=FailurePlan.parse("kill@4", seed=2),
        targets=[{"backend": "jax", "data_shards": 1}],
        checkpoint_dir=str(tmp_path), **kw)
    rec = _recovery(stats)
    assert _dump_map(res) == _dump_map(base)
    assert rec["lanes_resumed"] >= 1
    # the resumed lanes were evacuations that did NOT replay
    assert rec["evacuations"] >= rec["lanes_resumed"]
    ck = sorted(p.name for p in tmp_path.iterdir())
    assert any(n.endswith(".npz") for n in ck), ck


def test_hang_watchdog_detects_and_recovers(cfg, jobs, tmp_path):
    """A hung shard doesn't fail fast — the injector holds the barrier
    hostage until the watchdog's detect_after budget expires, then the
    supervisor treats it exactly like a kill (with a diagnostic)."""
    res, stats = supervised_serve(
        cfg, ListJobSource(jobs), plan=FailurePlan.parse("hang@2", seed=5),
        checkpoint_dir=str(tmp_path), detect_after=2,
        backend="pallas", resident=4, window=8, block=4)
    rec = _recovery(stats)
    assert rec["failures_detected"] == 1
    assert rec["migrations"] >= 1
    detected = [e for e in rec["events"]
                if e["event"] == "failure_detected"]
    assert detected[0]["kind"] == "hang"
    assert detected[0]["via"] == "watchdog"
    base, _ = serve(cfg, ListJobSource(jobs), backend="pallas",
                    resident=4, window=8, block=4)
    assert _dump_map(res) == _dump_map(base)


def test_poison_restarts_same_spec(cfg, jobs, tmp_path):
    """Poison is corruption, not loss of the backend: the supervisor
    re-runs the in-flight jobs on a fresh session of the *same* spec —
    an evacuation but no migration."""
    res, stats = supervised_serve(
        cfg, ListJobSource(jobs),
        plan=FailurePlan.parse("poison@2:1", seed=6),
        checkpoint_dir=str(tmp_path),
        backend="pallas", resident=4, window=8, block=4)
    rec = _recovery(stats)
    assert rec["failures_detected"] == 1
    assert rec["migrations"] == 0
    assert rec["evacuations"] >= 1
    base, _ = serve(cfg, ListJobSource(jobs), backend="pallas",
                    resident=4, window=8, block=4)
    assert _dump_map(res) == _dump_map(base)


def test_unfailed_supervised_run_adds_no_recovery_noise(cfg, jobs):
    """No plan, no checkpoint dir: the supervisor is a pass-through —
    same dumps, and no 'recovery' key polluting the stats."""
    base, _ = serve(cfg, ListJobSource(jobs), backend="pallas",
                    resident=4, window=8, block=4)
    res, stats = supervised_serve(
        cfg, ListJobSource(jobs), backend="pallas", resident=4,
        window=8, block=4)
    assert _dump_map(res) == _dump_map(base)
    assert "recovery" not in stats.occupancy


# -- the wire layer ---------------------------------------------------------


def _records(jobs):
    return [job_to_record(j) for j in jobs]


def test_dead_server_raises_connection_lost_not_hang():
    """A server that accepts but never speaks: every socket op carries
    the timeout, so the client surfaces ConnectionLost (after its
    retry budget) instead of blocking forever."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    try:
        with pytest.raises(ConnectionLost):
            WireClient(*srv.getsockname(), timeout_s=0.2, retries=1,
                       backoff_s=0.01)
    finally:
        srv.close()


def test_sever_mid_frame_resumes_with_idempotent_submit(cfg, jobs):
    """The server tears the connection mid-ACK at admission seq 2
    (a torn frame, then a hard close).  The client reconnects, resumes
    its session, resends — and gets the ORIGINAL seq back flagged
    ``dup``, so the admission transcript has no hole and every result
    still arrives exactly once."""
    recs = _records(jobs)
    src = WireJobSource(cfg, failures=FailurePlan.parse("sever@2", seed=3))
    acks, streamed, state = [], [], {}

    def client():
        cli = WireClient(*src.address, timeout_s=10.0, retries=4,
                         backoff_s=0.01, backoff_seed=3)
        for r in recs:
            acks.append(cli.submit(r))
        streamed.extend(cli.finish())
        state["retries"] = cli.retries
        state["session"] = cli.session
        cli.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    results, _ = serve(cfg, src, backend="pallas", resident=4,
                       window=8, block=4, emit=src.deliver)
    t.join(timeout=60)
    assert "retries" in state, "client thread died"
    assert state["retries"] == 1
    # the admission transcript is gap-free and the severed submit's
    # replayed ack carries its original seq
    assert [a["seq"] for a in acks] == list(range(len(recs)))
    assert acks[2].get("dup") is True
    assert not any(a.get("dup") for a in acks[:2] + acks[3:])
    assert sorted(r["id"] for r in streamed) == sorted(
        r["id"] for r in recs)
    assert sorted(r.job_id for r in results) == sorted(
        r["id"] for r in recs)


def test_heartbeats_reach_an_idle_connection(cfg):
    """heartbeat_s > 0: the server beacons idle connections, so a
    client can tell a slow backend from a dead one."""
    import time

    src = WireJobSource(cfg, heartbeat_s=0.01)
    try:
        cli = WireClient(*src.address, timeout_s=5.0)
        time.sleep(0.2)  # let beacons queue on the socket
        cli.finish()     # absorbs frames until BYE
        assert cli.heartbeats >= 1
        cli.close()
    finally:
        src.close()


def test_shed_threshold_sheds_batch_class_only(cfg, jobs):
    """Graceful degradation at the ledger: past the pending threshold
    a batch-class submit draws AdmissionShed (a structured NACK on the
    wire) while deadline traffic keeps being admitted — and the sheds
    are counted."""
    recs = _records(jobs)
    led = AdmissionLedger(credits=16, shed_threshold=2)
    assert led.register("c") == 16
    led.try_submit("c", dict(recs[0], deadline=8))
    led.try_submit("c", dict(recs[1], **{"class": "batch"}))
    with pytest.raises(AdmissionShed, match="shedding batch-class"):
        led.try_submit("c", dict(recs[2], **{"class": "batch"}))
    # AdmissionShed is an AdmissionReject: wire NACK machinery applies
    assert issubclass(AdmissionShed, AdmissionReject)
    # interactive traffic still flows past the threshold
    seq, _ = led.try_submit("c", dict(recs[3], deadline=8))
    assert seq == 2
    assert led.shed_jobs == 1


def test_wire_shed_nack_is_structured_and_counted(cfg, jobs):
    """End to end over the wire: shed NACKs carry ``shed: true`` (the
    client can tell 'resubmit later' from 'malformed') and the serving
    stats account every shed job."""
    recs = _records(jobs)
    for i, r in enumerate(recs):
        if i % 2:
            r["class"] = "batch"
        else:
            r["deadline"] = 8
    src = WireJobSource(cfg, shed_threshold=1)
    shed = []

    def client():
        with WireClient(*src.address) as cli:
            for r in recs:
                try:
                    cli.submit(r)
                except WireNack as e:
                    assert e.shed, e.payload
                    shed.append(r["id"])
            cli.finish()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    results, stats = serve(cfg, src, backend="pallas", resident=4,
                           window=8, block=4, emit=src.deliver)
    t.join(timeout=60)
    assert shed, "nothing shed at threshold 1"
    assert stats.occupancy.get("shed_jobs") == len(shed)
    served = {r.job_id for r in results}
    assert served.isdisjoint(shed)
    assert served | set(shed) == {r["id"] for r in recs}


# -- checkpoint schema v2 ---------------------------------------------------


def test_checkpoint_v2_carries_and_backfills_recovery_counters(tmp_path):
    import json

    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.utils.checkpoint import (
        RECOVERY_COUNTERS, load_state, save_state)
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    st = init_state_batched(
        cfg, *gen_uniform_random_arrays(cfg, 2, 16, seed=0))

    # v2 write: counters travel (zero-defaulted for missing names)
    p2 = str(tmp_path / "v2.npz")
    save_state(p2, st, cfg, extra_meta={"recovery": {"migrations": 3}})
    _, _, meta = load_state(p2, with_meta=True)
    assert meta["recovery"]["migrations"] == 3
    for name in RECOVERY_COUNTERS:
        assert name in meta["recovery"]

    # a v1 file (no meta_version, no recovery) loads with the counters
    # zero-backfilled instead of KeyErroring the supervisor
    with np.load(p2) as z:
        arrays = {k: z[k] for k in z.files if k != "meta_version"}
    extra = json.loads(str(arrays["meta_extra"]))
    extra.pop("recovery", None)
    arrays["meta_extra"] = np.array(json.dumps(extra))
    p1 = str(tmp_path / "v1.npz")
    np.savez(p1, **arrays)
    _, _, meta = load_state(p1, with_meta=True)
    assert meta["recovery"] == {n: 0 for n in RECOVERY_COUNTERS}

    # a newer-schema file refuses loudly
    with np.load(p2) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["meta_version"] = np.array(99)
    p9 = str(tmp_path / "v99.npz")
    np.savez(p9, **arrays)
    with pytest.raises(ValueError, match="newer"):
        load_state(p9)
