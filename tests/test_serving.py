"""Always-on serving (``hpa2_tpu.serving``): continuous-batching
ingest over resident lanes with overlapped host-device staging.

The contract under test (PERF.md "Always-on serving"):

1. **Bit-exactness** — a job served through the resident-lane loop
   must produce byte-identical final dumps to the same job run in a
   one-shot scheduled batch on the *same backend* (trace windowing
   legitimately changes cycle interleaving across backends, so the
   reference is per backend).  This must hold under shuffled arrival
   order, record/replay through the JSONL format, ``data_shards=2``,
   and fault injection.
2. **Zero recompiles** — after warmup every session program's jit
   cache holds exactly one entry; admission rides the fixed-shape
   barrier transform, never a new trace shape.
3. **Determinism of the feed layer** — JSONL records round-trip, and
   the seeded arrival processes are reproducible with the advertised
   mean rate.
"""

import dataclasses
import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from hpa2_tpu.config import FaultModel, Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.serving import (
    Job,
    ListJobSource,
    SocketJobSource,
    TracePool,
    job_from_record,
    job_to_record,
    parse_jobs_lines,
    poisson_arrivals,
    serve,
    synthetic_jobs,
    zipf_burst_arrivals,
)
from hpa2_tpu.serving.loop import _guard_compiles

ROBUST = Semantics().robust()

# one shared small feed: 8 zipf-length jobs, 4 resident lanes, so the
# loop really streams (admissions > resident) while staying fast on
# the CPU interpret path
_N_JOBS = 8
_SERVE_KW = dict(resident=4, window=8, block=4)


def _require_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


@pytest.fixture(scope="module")
def cfg():
    return SystemConfig(num_procs=4, semantics=ROBUST)


@pytest.fixture(scope="module")
def jobs(cfg):
    return synthetic_jobs(cfg, _N_JOBS, 24, seed=7, spread=3.0)


def _batch_arrays(jobs):
    return (
        np.stack([j.tr_op for j in jobs]),
        np.stack([j.tr_addr for j in jobs]),
        np.stack([j.tr_val for j in jobs]),
        np.stack([j.tr_len for j in jobs]),
    )


@pytest.fixture(scope="module")
def pallas_ref(cfg, jobs):
    """One-shot scheduled run of the same ensemble on the windowed
    Pallas path — the serving loop replays this exact schedule."""
    eng = PallasEngine(
        cfg, *_batch_arrays(jobs), block=4, trace_window=8,
        snapshots=False, schedule=Schedule(resident=4, fused=False),
    ).run()
    return {j.job_id: eng.system_final_dumps(s)
            for s, j in enumerate(jobs)}


def _assert_served_matches(results, ref, n=_N_JOBS):
    assert len(results) == n
    for r in results:
        assert r.dumps == ref[r.job_id], r.job_id


def _assert_zero_recompiles(stats):
    assert stats.compile_counts  # the guard actually saw programs
    for name, count in stats.compile_counts.items():
        assert count == 1, (name, count)


# -- served == one-shot, per backend ---------------------------------------


def test_pallas_served_matches_one_shot(cfg, jobs, pallas_ref):
    results, stats = serve(
        cfg, ListJobSource(jobs), backend="pallas", **_SERVE_KW
    )
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)
    assert stats.jobs_completed == _N_JOBS
    assert stats.occupancy["admissions"] == _N_JOBS
    # the phase split is populated and the wall clock covers it
    d = stats.as_dict()
    assert set(d["phases"]) == {
        "host_staging_s", "device_wait_s", "readback_s"
    }
    assert d["latency_s"]["p99"] >= d["latency_s"]["p50"] > 0


def test_pallas_serial_baseline_matches_one_shot(cfg, jobs, pallas_ref):
    """``overlap=False`` (the benchmark's serial baseline) is the same
    schedule with eager syncs — identical dumps, identical occupancy."""
    results, stats = serve(
        cfg, ListJobSource(jobs), backend="pallas", overlap=False,
        **_SERVE_KW
    )
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)
    assert stats.overlap is False


def test_shuffled_arrival_record_replay_byte_identical(
    cfg, jobs, pallas_ref
):
    """Jobs arriving in shuffled order, serialized to JSONL and parsed
    back (record/replay), still produce byte-identical dumps — job
    identity travels with the job, not the lane it lands in."""
    perm = np.random.default_rng(11).permutation(_N_JOBS)
    shuffled = [jobs[i] for i in perm]
    lines = [json.dumps(job_to_record(j)) for j in shuffled]
    replayed = parse_jobs_lines(cfg, lines)
    assert [j.job_id for j in replayed] == [j.job_id for j in shuffled]

    results, stats = serve(
        cfg, ListJobSource(replayed), backend="pallas", **_SERVE_KW
    )
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)

    # and the longest-first policy reorders admission without touching
    # any job's bytes
    results_lf, _ = serve(
        cfg, ListJobSource(replayed), backend="pallas",
        policy="longest-first", **_SERVE_KW
    )
    _assert_served_matches(results_lf, pallas_ref)


def test_jax_served_matches_one_shot(cfg, jobs):
    from hpa2_tpu.ops.engine import BatchJaxEngine

    ref_eng = BatchJaxEngine(
        cfg, [j.batch_traces() for j in jobs],
        schedule=Schedule(resident=2, fused=False),
    ).run()
    ref = {j.job_id: ref_eng.system_final_dumps(s)
           for s, j in enumerate(jobs)}
    for overlap in (True, False):
        results, stats = serve(
            cfg, ListJobSource(jobs), backend="jax", resident=2,
            max_trace_len=32, interval=64, overlap=overlap,
        )
        _assert_served_matches(results, ref)
        _assert_zero_recompiles(stats)


def test_jax_served_fault_injection_matches_one_shot(cfg, jobs):
    """The XLA backend's fault layer survives serving: per-system rng
    keys are independent of the row a job lands in."""
    import dataclasses

    from hpa2_tpu.ops.engine import BatchJaxEngine

    fcfg = dataclasses.replace(
        cfg,
        fault=FaultModel(drop=0.2, duplicate=0.1, reorder=0.1, seed=13),
    )
    ref_eng = BatchJaxEngine(
        fcfg, [j.batch_traces() for j in jobs],
        schedule=Schedule(resident=2, fused=False),
    ).run()
    ref = {j.job_id: ref_eng.system_final_dumps(s)
           for s, j in enumerate(jobs)}
    assert ref_eng.stats()["fault_retransmissions"] > 0
    results, stats = serve(
        fcfg, ListJobSource(jobs), backend="jax", resident=2,
        max_trace_len=32, interval=64,
    )
    _assert_served_matches(results, ref)
    _assert_zero_recompiles(stats)


@pytest.mark.virtual_mesh
def test_sharded_served_matches_one_shot(cfg, jobs):
    """data_shards=2: the serving loop drives shard-local admission
    queues; dumps match the one-shot sharded scheduled run."""
    _require_devices(2)
    from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

    ref_eng = DataShardedPallasEngine(
        cfg, *_batch_arrays(jobs), data_shards=2, block=4,
        trace_window=8, snapshots=False,
        schedule=Schedule(resident=4, fused=False),
    ).run()
    ref = {j.job_id: ref_eng.system_final_dumps(s)
           for s, j in enumerate(jobs)}
    results, stats = serve(
        cfg, ListJobSource(jobs), backend="pallas-sharded",
        data_shards=2, **_SERVE_KW
    )
    _assert_served_matches(results, ref)
    _assert_zero_recompiles(stats)


def test_admission_policies_serve_byte_identical(cfg, jobs, pallas_ref):
    """deadline-edf and fair-drr reorder *admission* only: per-job
    dumps stay byte-identical to the one-shot reference, and the
    occupancy report grows the deadline / tenant-share columns."""
    tagged = [
        dataclasses.replace(
            j, tenant=("a", "b")[i % 2], deadline=(8, 32, -1)[i % 3]
        )
        for i, j in enumerate(jobs)
    ]
    with_deadline = sum(1 for j in tagged if j.deadline >= 0)

    results, stats = serve(
        cfg, ListJobSource(tagged), backend="pallas",
        policy="deadline-edf", **_SERVE_KW
    )
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)
    occ = stats.occupancy
    assert occ["deadline_met"] + occ["deadline_missed"] == with_deadline

    results, stats = serve(
        cfg, ListJobSource(tagged), backend="pallas",
        policy="fair-drr", tenant_weights={"a": 2.0, "b": 1.0},
        **_SERVE_KW
    )
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)
    occ = stats.occupancy
    assert occ["deadline_met"] + occ["deadline_missed"] == with_deadline
    share = occ["tenant_share"]
    assert len(share) == 2
    assert abs(sum(share.values()) - 1.0) < 1e-6


@pytest.mark.virtual_mesh
def test_node_sharded_served_matches_one_shot(cfg, jobs):
    """node_shards=2: resident lanes whose NODE planes split across a
    device mesh; dumps match the one-shot node-sharded scheduled run."""
    _require_devices(2)
    from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine

    ref_eng = NodeShardedPallasEngine(
        cfg, *_batch_arrays(jobs), node_shards=2, block=4,
        trace_window=8, snapshots=False,
        schedule=Schedule(resident=4, fused=True),
    ).run()
    ref = {j.job_id: ref_eng.system_final_dumps(s)
           for s, j in enumerate(jobs)}
    results, stats = serve(
        cfg, ListJobSource(jobs), backend="pallas-node-sharded",
        node_shards=2, **_SERVE_KW
    )
    _assert_served_matches(results, ref)
    _assert_zero_recompiles(stats)


def test_jax_served_protocol_variant_matches_one_shot(cfg, jobs):
    """The PR-13 protocol variants survive serving: a moesi config
    served on the jax backend matches its own one-shot run."""
    from hpa2_tpu.ops.engine import BatchJaxEngine

    mcfg = dataclasses.replace(cfg, protocol="moesi")
    ref_eng = BatchJaxEngine(
        mcfg, [j.batch_traces() for j in jobs],
        schedule=Schedule(resident=2, fused=False),
    ).run()
    ref = {j.job_id: ref_eng.system_final_dumps(s)
           for s, j in enumerate(jobs)}
    results, stats = serve(
        mcfg, ListJobSource(jobs), backend="jax", resident=2,
        max_trace_len=32, interval=64,
    )
    _assert_served_matches(results, ref)
    _assert_zero_recompiles(stats)


# -- the zero-recompile guard ----------------------------------------------


def test_compile_guard_trips_on_recompile():
    _guard_compiles({"runner": 1, "barrier": 1}, True)  # fine
    with pytest.raises(RuntimeError, match="recompil"):
        _guard_compiles({"runner": 2, "barrier": 1}, True)
    _guard_compiles({"runner": 2}, False)  # disabled guard never trips


# -- the trace pool --------------------------------------------------------


def test_trace_pool_compaction_preserves_windows(cfg):
    """Freeing retired systems accumulates waste; once waste beats the
    live half the pool compacts.  System ids are stable and window
    assembly after compaction is bit-identical to a fresh pool."""
    window = 8
    jobs = synthetic_jobs(cfg, 12, 24, seed=3, spread=3.0)
    pool = TracePool(cfg, window, capacity=window)
    ids = [pool.add(j) for j in jobs]
    assert ids == list(range(12))

    fresh = TracePool(cfg, window)
    for j in jobs:
        fresh.add(j)

    survivors = [s for s in ids if s % 3 == 0]

    def _windows(p):
        lanes = np.arange(len(survivors))
        lane_sys = np.asarray(survivors)
        lane_seg = np.zeros(len(survivors), np.int64)
        return p.windows(lanes, lane_sys, lane_seg,
                         len(survivors))

    before = _windows(pool)
    used_before = pool._used
    for s in ids:
        if s not in survivors:
            pool.free(s)
    # the waste threshold really tripped: freed columns reclaimed
    assert pool._waste == 0 and pool._used < used_before
    after = _windows(pool)
    ref = _windows(fresh)
    for got in (before, after):
        assert np.array_equal(got[0], ref[0])
        assert np.array_equal(got[1], ref[1])


# -- JSONL format ----------------------------------------------------------


def test_job_record_roundtrip(cfg):
    job = synthetic_jobs(cfg, 1, 16, seed=5)[0]
    rec = job_to_record(job)
    back = job_from_record(cfg, rec)
    assert back.job_id == job.job_id
    assert np.array_equal(back.tr_len, job.tr_len)
    # compare within each node's length; a read carries no value, so
    # tr_val only survives at write slots
    t = back.tr_op.shape[1]
    valid = np.arange(t)[None, :] < job.tr_len[:, None]
    assert np.array_equal(back.tr_op[valid], job.tr_op[:, :t][valid])
    assert np.array_equal(back.tr_addr[valid],
                          job.tr_addr[:, :t][valid])
    wr = valid & (back.tr_op == 1)
    assert np.array_equal(back.tr_val[wr], job.tr_val[:, :t][wr])


def test_job_record_workload_form_and_errors(cfg):
    rec = {"id": "w0", "workload": {"kind": "uniform", "instrs": 16,
                                    "seed": 9}}
    a = job_from_record(cfg, rec)
    b = job_from_record(cfg, rec)
    assert a.tr_op.shape == (cfg.num_procs, 16)
    assert np.array_equal(a.tr_addr, b.tr_addr)  # seeded => replayable

    with pytest.raises(ValueError, match="'id'"):
        job_from_record(cfg, {"traces": [[]] * cfg.num_procs})
    with pytest.raises(ValueError, match="exactly one"):
        job_from_record(cfg, {"id": "x", "traces": [], "workload": {}})
    with pytest.raises(ValueError, match="one trace per node"):
        job_from_record(cfg, {"id": "x", "traces": [[["R", 0]]]})
    with pytest.raises(ValueError, match="bad JSON"):
        parse_jobs_lines(cfg, ["{nope"])


# -- job sources + arrival processes ---------------------------------------


def test_socket_source_feeds_serving(cfg, jobs, pallas_ref):
    src = SocketJobSource(cfg)
    lines = [json.dumps(job_to_record(j)) for j in jobs]
    lines.append(json.dumps({"eof": True}))

    def _feed():
        with socket.create_connection(src.address) as conn:
            conn.sendall(("\n".join(lines) + "\n").encode())

    t = threading.Thread(target=_feed, daemon=True)
    t.start()
    try:
        results, stats = serve(
            cfg, src, backend="pallas", **_SERVE_KW
        )
    finally:
        src.close()
    t.join(timeout=5)
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)


def test_socket_source_survives_abrupt_disconnect(cfg, jobs, pallas_ref):
    """A client that RSTs mid-line must not take the source down:
    every complete record already sent stays queued, the partial line
    is dropped, and a later connection still finishes the feed."""
    src = SocketJobSource(cfg)
    try:
        first = socket.create_connection(src.address)
        payload = "".join(
            json.dumps(job_to_record(j)) + "\n" for j in jobs[:3]
        )
        # a partial record with no newline, then an abortive close
        payload += json.dumps(job_to_record(jobs[3]))[:20]
        first.sendall(payload.encode())
        first.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            struct.pack("ii", 1, 0),
        )
        first.close()  # RST, not FIN

        # the three complete records survive; the partial one is gone
        deadline = time.monotonic() + 10.0
        while src._queue.qsize() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert src._queue.qsize() == 3

        lines = [json.dumps(job_to_record(j)) for j in jobs[3:]]
        lines.append(json.dumps({"eof": True}))
        with socket.create_connection(src.address) as second:
            second.sendall(("\n".join(lines) + "\n").encode())

        results, stats = serve(cfg, src, backend="pallas", **_SERVE_KW)
    finally:
        src.close()
    _assert_served_matches(results, pallas_ref)
    _assert_zero_recompiles(stats)


def test_timed_list_source_releases_on_arrival(cfg):
    jobs = synthetic_jobs(
        cfg, 4, 8, seed=1, arrivals=np.array([0.0, 0.0, 60.0, 60.0])
    )
    src = ListJobSource(jobs, timed=True)
    first = src.poll()
    assert [j.job_id for j in first] == ["job-00000", "job-00001"]
    assert not src.exhausted  # two jobs still an hour out
    assert src.poll() == []


def test_arrival_processes_seeded_and_rate_matched():
    for gen in (poisson_arrivals, zipf_burst_arrivals):
        a = gen(2000, 50.0, seed=4)
        b = gen(2000, 50.0, seed=4)
        assert np.array_equal(a, b)
        assert a.shape == (2000,)
        assert np.all(np.diff(a) >= 0)
        mean_rate = 2000 / a[-1]
        assert 0.7 * 50.0 <= mean_rate <= 1.3 * 50.0, gen.__name__
    # the heavy tail really is heavy: zipf has instants with many
    # simultaneous arrivals, poisson essentially never does
    z = zipf_burst_arrivals(2000, 50.0, seed=4)
    _, counts = np.unique(z, return_counts=True)
    assert counts.max() >= 4
    with pytest.raises(ValueError):
        poisson_arrivals(10, 0.0)
