"""Event-driven cycle elision (ISSUE-12).

Three layers of protection:

* a **jaxpr guard** pinning the elided hot loop's added structure to
  exactly one reduction (the jump-distance ``reduce_min``) and one
  ``cond`` (fast-forward vs lockstep select) — the lockstep phase
  machinery moves inside the cond branch, and nothing else (no new
  while/scan/dot_general) may appear at the loop's top level;
* **bit-exactness sweeps**: dumps, final cycle counts, and all
  non-elision stats must be byte-identical between ``elide=True`` and
  the ``elide=False`` escape hatch across schedules, sharding, fault
  injection, and topology — and the lockstep Pallas path (packed
  planes included) must keep matching while reporting zero elision;
* the **exact-replay model** (analysis/elision.py): predicted
  ``elided_cycles`` / ``multi_hit_retired`` equal the device counters
  bit-for-bit, including per-interval totals under the chunked
  scheduled loop.
"""

import dataclasses

import numpy as np
import pytest

import jax

from hpa2_tpu.analysis.elision import (
    predicted_batch_elision,
    predicted_elision,
)
from hpa2_tpu.config import (
    FaultModel,
    InterconnectConfig,
    Semantics,
    SystemConfig,
)
from hpa2_tpu.models.spec_engine import StallDiagnostic
from hpa2_tpu.ops.engine import BatchJaxEngine, JaxEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.utils.trace import gen_hot_hit_zipf, gen_uniform_random

ROBUST = Semantics().robust()
_ELISION_KEYS = ("elided_cycles", "multi_hit_retired")


def _cfg(**kw):
    return SystemConfig(num_procs=4, semantics=ROBUST, **kw)


def _strip(stats: dict) -> dict:
    return {k: v for k, v in stats.items() if k not in _ELISION_KEYS}


def _run_pair(cfg, traces, **kw):
    on = JaxEngine(cfg, traces, **kw).run()
    off = JaxEngine(
        dataclasses.replace(cfg, elide=False), traces, **kw
    ).run()
    return on, off


def _assert_single_exact(on: JaxEngine, off: JaxEngine):
    assert int(on.state.cycle) == int(off.state.cycle)
    assert on.final_dumps() == off.final_dumps()
    assert on.snapshots() == off.snapshots()
    assert _strip(on.stats()) == _strip(off.stats())
    assert not any(k in off.stats() for k in _ELISION_KEYS)


# -- jaxpr guard ------------------------------------------------------


def test_elided_loop_jaxpr_guard():
    """The event-driven loop body adds ONE reduction (the jump min)
    and ONE cond (fast-forward vs lockstep) at its top level, nothing
    else: the propose computation is elementwise + that reduce_min,
    and the whole lockstep step lives inside the cond branches (so it
    no longer appears at the top level at all).

    The counts themselves live in the `xla-run-loop` contract
    (analysis/contracts.py); this test asserts the measurement still
    reproduces the historical pins and that the checked-in contract
    carries exactly those expectations — no guard weakened."""
    from hpa2_tpu.analysis.contracts import measure_run_loop, registry

    obs = measure_run_loop(_cfg())
    counts = {
        k: obs.values[f"elided.{k}"]
        for k in ("reduce_min", "cond", "while", "scan", "dot_general",
                  "sort")
    }
    assert counts == {
        "reduce_min": 1, "cond": 1, "while": 0, "scan": 0,
        "dot_general": 0, "sort": 0,
    }, counts
    # the escape hatch rebuilds the pure lockstep body: phase ops back
    # at the top level, no jump cond anywhere
    assert obs.values["lockstep.cond"] == 0
    assert obs.values["lockstep.extra_eqns"] > 0
    # and the declarative contract pins the same invariants
    contract = next(c for c in registry() if c.name == "xla-run-loop")
    rules = {r.key: (r.op, r.expect) for r in contract.rules}
    assert rules["elided.reduce_min"] == ("==", 1)
    assert rules["elided.cond"] == ("==", 1)
    assert rules["lockstep.cond"] == ("==", 0)


# -- bit-exactness sweeps ---------------------------------------------


def test_bit_exact_plain():
    cfg = _cfg()
    on, off = _run_pair(cfg, gen_hot_hit_zipf(cfg, 64, seed=1))
    _assert_single_exact(on, off)
    assert on.stats()["elided_cycles"] > 0
    assert on.stats()["multi_hit_retired"] > 0


def test_bit_exact_miss_heavy():
    # uniform-random global traffic barely elides — the candidate
    # logic must stay exact when almost every cycle is eventful
    cfg = _cfg()
    on, off = _run_pair(cfg, gen_uniform_random(cfg, 48, seed=2))
    _assert_single_exact(on, off)


def test_bit_exact_fault_injection():
    # the fast-forward must replay the per-cycle PRNG splits exactly
    cfg = _cfg(
        fault=FaultModel(drop=0.2, duplicate=0.1, reorder=0.1, seed=7)
    )
    on, off = _run_pair(cfg, gen_hot_hit_zipf(cfg, 64, seed=3))
    _assert_single_exact(on, off)
    assert on.stats()["elided_cycles"] > 0


def test_bit_exact_mesh2d_topology():
    # deliver_at gating: idle jumps ride the head in-transit stamps
    cfg = _cfg(interconnect=InterconnectConfig(topology="mesh2d"))
    on, off = _run_pair(cfg, gen_hot_hit_zipf(cfg, 64, seed=3))
    _assert_single_exact(on, off)
    assert on.stats()["elided_cycles"] > 0


def _batch_pair(cfg, batch, **kw):
    on = BatchJaxEngine(cfg, batch, **kw).run()
    off = BatchJaxEngine(
        dataclasses.replace(cfg, elide=False), batch, **kw
    ).run()
    return on, off


def _assert_batch_exact(cfg, on: BatchJaxEngine, off: BatchJaxEngine):
    for b in range(on.b):
        assert on.system_final_dumps(b) == off.system_final_dumps(b)
        assert on.system_snapshots(b) == off.system_snapshots(b)
    assert _strip(on.stats()) == _strip(off.stats())
    assert not any(k in off.stats() for k in _ELISION_KEYS)


def _zipf_batch(cfg, b, t, seed0=0):
    return [gen_hot_hit_zipf(cfg, t, seed=seed0 + s) for s in range(b)]


def test_bit_exact_batched():
    cfg = _cfg()
    batch = _zipf_batch(cfg, 4, 48)
    on, off = _batch_pair(cfg, batch)
    _assert_batch_exact(cfg, on, off)
    assert np.asarray(on.state.cycle).tolist() == \
        np.asarray(off.state.cycle).tolist()
    assert on.stats()["elided_cycles"] > 0


def test_bit_exact_fused_schedule():
    cfg = _cfg()
    batch = _zipf_batch(cfg, 6, 48)
    on, off = _batch_pair(
        cfg, batch, schedule=Schedule(resident=2, fused=True)
    )
    _assert_batch_exact(cfg, on, off)
    assert on.occupancy.as_dict()["elided_cycles"] > 0
    assert "elided_cycles" not in off.occupancy.as_dict()


def test_bit_exact_host_loop_schedule():
    cfg = _cfg()
    batch = _zipf_batch(cfg, 4, 48)
    on, off = _batch_pair(
        cfg, batch, schedule=Schedule(interval=16, fused=False)
    )
    _assert_batch_exact(cfg, on, off)
    assert on.occupancy.as_dict()["elided_cycles"] > 0


def test_bit_exact_data_sharded():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    cfg = _cfg()
    batch = _zipf_batch(cfg, 4, 48)
    on, off = _batch_pair(cfg, batch, data_shards=2)
    _assert_batch_exact(cfg, on, off)
    assert on.stats()["elided_cycles"] > 0
    # and sharded == unsharded with elision on (the psum-min jump must
    # not desync the shard-local schedules)
    ref = BatchJaxEngine(cfg, batch).run()
    assert _strip(ref.stats()) == _strip(on.stats())
    for b in range(on.b):
        assert on.system_final_dumps(b) == ref.system_final_dumps(b)


def test_bit_exact_node_sharded():
    """Config.elide at node_shards > 1 (ISSUE-15: the jump proposal
    folded with a psum-min over BOTH mesh axes): byte-identical to the
    lockstep sharded run AND the single-chip elided run, with real
    elision on the hot-hit workload."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    from hpa2_tpu.parallel import NodeShardedEngine, make_mesh

    cfg = _cfg()
    traces = gen_hot_hit_zipf(cfg, 64, seed=1)
    on = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=2)
    ).run()
    off = NodeShardedEngine(
        dataclasses.replace(cfg, elide=False), traces,
        mesh=make_mesh(node_shards=2),
    ).run()
    assert on.cycle == off.cycle
    assert on.final_dumps() == off.final_dumps()
    assert on.snapshots() == off.snapshots()
    assert _strip(on.stats()) == _strip(off.stats())
    assert on.stats()["elided_cycles"] > 0
    # the single-chip elided engine agrees on every architectural fact
    ref = JaxEngine(cfg, traces).run()
    assert on.cycle == int(ref.state.cycle)
    assert on.final_dumps() == ref.final_dumps()
    assert on.snapshots() == ref.snapshots()


def test_bit_exact_grid_2x2_mesh():
    """Elision on the full 2-D (data, node) mesh: batched proposals
    reduce locally, one pmin over both axes makes the global jump."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 local devices")
    from hpa2_tpu.parallel import GridEngine, make_mesh

    cfg = _cfg()
    batch = _zipf_batch(cfg, 2, 48)
    mesh = make_mesh(node_shards=2, data_shards=2)
    on = GridEngine(cfg, batch, mesh=mesh).run()
    off = GridEngine(
        dataclasses.replace(cfg, elide=False), batch, mesh=mesh
    ).run()
    ref = BatchJaxEngine(cfg, batch).run()
    for b in range(len(batch)):
        assert on.system_snapshots(b) == off.system_snapshots(b)
        assert on.system_snapshots(b) == ref.system_snapshots(b)
    assert int(np.sum(np.asarray(on.state.n_elided))) > 0
    assert int(np.sum(np.asarray(off.state.n_elided))) == 0


def test_watchdog_agreement_node_sharded():
    """The sharded elided run trips the watchdog at the same simulated
    cycle as the single-chip run — the shard-local issuer key in the
    propose can only shrink jumps, never overshoot the trip point."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 local devices")
    from hpa2_tpu.parallel import NodeShardedEngine, make_mesh

    cfg = _cfg(
        fault=FaultModel(drop=1.0, edge_sender=1, edge_receiver=0,
                         seed=1)
    )
    traces = gen_uniform_random(cfg, 16, seed=3)
    ref = JaxEngine(cfg, traces, watchdog_cycles=50)
    with pytest.raises(StallDiagnostic) as ref_ei:
        ref.run()
    shd = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=2),
        watchdog_cycles=50,
    )
    with pytest.raises(StallDiagnostic) as shd_ei:
        shd.run()
    assert "watchdog" in str(shd_ei.value)
    assert shd_ei.value.cycle == ref_ei.value.cycle


def test_pallas_lockstep_unaffected_packed_planes():
    """The Pallas family (packed planes included) accepts the elide
    knob but runs lockstep: zero elision counters, results identical
    to the elided XLA run."""
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import traces_to_arrays

    cfg = _cfg()
    traces = gen_hot_hit_zipf(cfg, 32, seed=5)
    arrays = traces_to_arrays(cfg, [traces])
    pal = PallasEngine(
        cfg, *arrays, block=1, cycles_per_call=8, interpret=True,
        packed=True,
    ).run()
    assert not any(k in pal.stats() for k in _ELISION_KEYS)
    xla = JaxEngine(cfg, traces).run()
    assert xla.stats()["elided_cycles"] > 0
    assert pal.system_final_dumps(0) == xla.final_dumps()
    assert pal.system_snapshots(0) == xla.snapshots()


# -- acceptance: >= 2x device-step reduction --------------------------


def test_two_x_step_reduction_on_zipf():
    """On the Zipf private hot-set workload at spread 8 the elided run
    must collapse at least half of all simulated cycles — i.e. the
    device executes <= cycle/2 steps (measured ~3x at these knobs)."""
    cfg = _cfg()
    traces = gen_hot_hit_zipf(
        cfg, 400, seed=3, write_frac=0.3, spread=8.0, tail=0.01
    )
    on, off = _run_pair(cfg, traces)
    _assert_single_exact(on, off)
    cycle = int(on.state.cycle)
    elided = on.stats()["elided_cycles"]
    assert elided >= cycle / 2, (
        f"only {elided} of {cycle} cycles elided (< 2x step reduction)"
    )


# -- watchdog semantics under elision ---------------------------------


def test_watchdog_counts_simulated_cycles():
    """A stalled system must still trip the watchdog — at the same
    simulated cycle as lockstep — with elision on: the watchdog
    measures simulated cycles, not device steps."""
    cfg = _cfg(
        fault=FaultModel(drop=1.0, edge_sender=1, edge_receiver=0,
                         seed=1)
    )
    traces = gen_uniform_random(cfg, 16, seed=3)
    cycles = []
    for elide in (True, False):
        eng = JaxEngine(
            dataclasses.replace(cfg, elide=elide), traces,
            watchdog_cycles=50,
        )
        with pytest.raises(StallDiagnostic) as ei:
            eng.run()
        assert "watchdog" in str(ei.value)
        cycles.append(ei.value.cycle)
    assert cycles[0] == cycles[1]


# -- exact-replay model ----------------------------------------------


def test_model_matches_device_counters():
    cfg = _cfg()
    traces = gen_hot_hit_zipf(cfg, 96, seed=4)
    pred = predicted_elision(cfg, traces)
    eng = JaxEngine(cfg, traces).run()
    stats = eng.stats()
    assert pred.cycles == int(eng.state.cycle)
    assert pred.elided_cycles == stats.get("elided_cycles", 0)
    assert pred.multi_hit_retired == stats.get("multi_hit_retired", 0)
    assert pred.device_steps == pred.cycles - pred.elided_cycles


def test_model_matches_device_counters_topology():
    cfg = _cfg(interconnect=InterconnectConfig(topology="mesh2d"))
    traces = gen_hot_hit_zipf(cfg, 96, seed=4)
    pred = predicted_elision(cfg, traces)
    eng = JaxEngine(cfg, traces).run()
    assert pred.cycles == int(eng.state.cycle)
    assert pred.elided_cycles == eng.stats().get("elided_cycles", 0)


def test_model_per_interval_matches_scheduled_run():
    """The occupancy-model extension: per-interval elided totals from
    the batched shared-jump replay sum to — and interval-count with —
    the real scheduled run's counters."""
    cfg = _cfg()
    batch = _zipf_batch(cfg, 3, 80)
    pred = predicted_batch_elision(cfg, batch, interval=24)
    eng = BatchJaxEngine(
        cfg, batch, schedule=Schedule(interval=24, fused=False)
    ).run()
    occ = eng.occupancy.as_dict()
    assert sum(pred.per_interval) == pred.elided_cycles
    assert pred.elided_cycles == occ["elided_cycles"]
    assert pred.multi_hit_retired == occ["multi_hit_retired"]
    assert len(pred.per_interval) == occ["intervals"]


def test_elision_table_verifies():
    from hpa2_tpu.analysis.elision import elision_table

    table, rc = elision_table(procs=4, instrs=64, spreads=(8.0,))
    assert rc == 0, table
    assert "exact match" in table
