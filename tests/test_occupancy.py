"""Occupancy scheduler (``schedule=`` on both ensemble engines).

Three properties pin the design (PERF.md "Occupancy scheduler"):

1. **Bit-exactness** — systems are independent along the lane/row
   axis, so compacting, backfilling, and streaming them through the
   device must leave every per-system final dump (and, on the Pallas
   engine, the whole per-system scalars plane) bit-identical to the
   unscheduled run — including under ``data_shards=`` and fault
   injection.
2. **The win** — on a heterogeneous (zipf) workload the scheduled run
   executes >= 2x fewer block-segments than the unscheduled lockstep
   bound, measured from real run counters.
3. **Zero hot-loop cost** — one scheduling interval IS the unscheduled
   run program built at ``n_seg=1``: the lru-cached builder returns
   the identical function object, so the cycle loop provably gains no
   gather/scatter/DMA (an identity is the strongest jaxpr guard).
   Compaction ops live only in the separate jitted barrier transform.

The static model (``analysis occupancy``) replays the same policy, so
its predicted counters must equal the measured ones exactly.
"""

import dataclasses

import numpy as np
import pytest

from hpa2_tpu.config import FaultModel, Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine, _build_stream_run
from hpa2_tpu.ops.schedule import (
    LaneScheduler,
    Schedule,
    lockstep_block_segments,
    segments_needed,
    simulate,
)
from hpa2_tpu.utils.trace import (
    gen_heterogeneous_random_arrays,
    gen_uniform_random,
    heterogeneous_lengths,
)

ROBUST = Semantics().robust()

# interpret-mode runs are slow: one shared small geometry for the
# exactness tests, one larger zipf geometry for the >= 2x acceptance
# test (5 blocks of 8 lanes; max/median = 8x at this seed)
_KW = dict(block=4, cycles_per_call=32, snapshots=False, trace_window=8,
           gate=True)
_ZIPF_KW = dict(block=8, cycles_per_call=32, snapshots=False,
                trace_window=8, gate=True)


def _require_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


@pytest.fixture(scope="module")
def cfg():
    return SystemConfig(num_procs=4, semantics=ROBUST)


@pytest.fixture(scope="module")
def small_zipf(cfg):
    """(arrays, unscheduled reference engine) at the shared small
    geometry: batch 24, zipf lengths 8..32."""
    arrays = gen_heterogeneous_random_arrays(
        cfg, 24, 32, dist="zipf", spread=4.0, seed=1
    )
    ref = PallasEngine(cfg, *arrays, **_KW).run()
    return arrays, ref


def _dumps_match(eng, ref, batch):
    return all(
        eng.system_final_dumps(s) == ref.system_final_dumps(s)
        for s in range(batch)
    )


# -- the static model / policy ---------------------------------------------


def test_model_never_beats_lockstep_and_conserves_work():
    rng = np.random.default_rng(0)
    for _ in range(20):
        b = int(rng.integers(4, 40))
        block = int(rng.choice([1, 2, 4]))
        b -= b % (2 * block)
        if b < 2 * block:
            continue
        nseg = rng.integers(1, 9, size=b)
        r = b // 2 * 2
        st = simulate(nseg, resident=r, block=block, groups=1,
                      threshold=0.5)
        # every system runs every one of its segments exactly once
        assert st.live_lane_intervals == int(nseg.sum())
        assert st.lockstep_block_segments == lockstep_block_segments(
            nseg, block
        )
        assert st.block_segments <= st.lockstep_block_segments
        assert st.admissions == b - r


def test_segments_needed_from_length_plane():
    tr_len = np.array([[3, 8, 0], [9, 1, 0]])  # [N=2, B=3]
    assert segments_needed(tr_len, 4).tolist() == [3, 2, 1]


def test_scheduler_rejects_bad_shapes():
    nseg = np.ones(8, dtype=np.int64)
    with pytest.raises(ValueError):
        LaneScheduler(nseg, resident=6, block=4)  # 6 % 4 != 0
    with pytest.raises(ValueError):
        LaneScheduler(nseg, resident=8, block=4, groups=3)
    with pytest.raises(ValueError):
        PallasEngine(
            SystemConfig(num_procs=4, semantics=ROBUST),
            *gen_heterogeneous_random_arrays(
                SystemConfig(num_procs=4, semantics=ROBUST), 8, 16
            ),
            schedule=Schedule(), snapshots=True,
        )


# -- zero hot-loop cost (jaxpr guard) --------------------------------------


def test_interval_is_the_unscheduled_program(cfg, small_zipf):
    """The scheduler's per-interval program must BE the unscheduled
    n_seg=1 run program — the lru-cached builder returns the identical
    object, so scheduling adds zero ops (no gather/scatter/DMA) to the
    while-to-quiescence cycle loop.  Lane permutation and admission
    resets live only in the separate jitted barrier transform."""
    arrays, _ = small_zipf
    eng = PallasEngine(cfg, *arrays, schedule=Schedule(), **_KW)
    max_cycles = 10_000
    max_calls = max(1, -(-max_cycles // eng.cycles_per_call))
    assert eng._interval_runner(max_cycles) is _build_stream_run(
        cfg, eng._resident, eng.block, eng.cycles_per_call,
        eng._interpret, False, eng._window, 1, max_calls, frozenset(),
        True, False,
    )
    # the barrier transform is a different function entirely
    assert eng._barrier_fn() is not eng._interval_runner(max_cycles)


# -- bit-exactness + the >= 2x win -----------------------------------------


def test_zipf_scheduled_2x_fewer_block_segments_bit_exact(cfg):
    """Acceptance geometry: 40 systems in 5 blocks of 8, zipf trace
    lengths with an 8x max/median spread.  The scheduled run must do
    >= 2x fewer block-segments than the unscheduled lockstep bound
    (real run counters, CPU interpret path) while every per-system
    dump AND the whole per-system scalars plane stay bit-identical.
    The static model must predict the measured counters exactly."""
    arrays = gen_heterogeneous_random_arrays(
        cfg, 40, 64, dist="zipf", spread=8.0, seed=2
    )
    lens = heterogeneous_lengths(40, 64, dist="zipf", spread=8.0, seed=2)
    med = float(np.median(lens))
    assert lens.max() / med >= 4.0  # the workload really is skewed

    ref = PallasEngine(cfg, *arrays, **_ZIPF_KW).run()
    eng = PallasEngine(cfg, *arrays, schedule=Schedule(), **_ZIPF_KW)
    assert eng.b // eng.block >= 4  # >= 4 blocks, per the bar
    eng.run()

    occ = eng.occupancy
    assert occ.block_segments * 2 <= occ.lockstep_block_segments
    assert occ.compactions > 0

    assert _dumps_match(eng, ref, 40)
    assert np.array_equal(
        np.asarray(eng.state["scalars"]), np.asarray(ref.state["scalars"])
    )

    # exact-replay model pinning (trivially satisfies the 10% band)
    model = simulate(
        segments_needed(eng._tr_len_np, eng._window),
        resident=eng._resident, block=eng.block, groups=1,
        threshold=eng.schedule.threshold,
    )
    assert model.block_segments == occ.block_segments
    assert model.lockstep_block_segments == occ.lockstep_block_segments
    assert model.compactions == occ.compactions
    assert model.admissions == occ.admissions

    from hpa2_tpu.analysis.occupancy import predicted_stats

    pred = predicted_stats(lens, _ZIPF_KW["trace_window"], eng.block)
    assert pred.block_segments == occ.block_segments

    # the fused path (the default above) must match the PR-5
    # host-barrier loop bit-for-bit at the 8x-zipf geometry, differing
    # only in launch accounting: one device program instead of one
    # per interval
    eng5 = PallasEngine(
        cfg, *arrays, schedule=Schedule(fused=False), **_ZIPF_KW
    ).run()
    assert np.array_equal(
        np.asarray(eng.state["scalars"]),
        np.asarray(eng5.state["scalars"]),
    )
    d, d5 = occ.as_dict(), eng5.occupancy.as_dict()
    assert d["host_barriers"] == 0 and d["device_programs"] == 1
    assert d5["host_barriers"] == d5["intervals"] > 1
    assert d5["device_programs"] == d5["intervals"]
    strip = ("host_barriers", "device_programs")
    assert {k: v for k, v in d.items() if k not in strip} == (
        {k: v for k, v in d5.items() if k not in strip}
    )


def test_streaming_resident_bit_exact(cfg, small_zipf):
    """resident < batch: the ensemble streams through the device via
    the admission queue; dumps stay bit-exact."""
    arrays, ref = small_zipf
    eng = PallasEngine(
        cfg, *arrays, schedule=Schedule(resident=8), **_KW
    ).run()
    assert eng.occupancy.admissions == 24 - 8
    assert _dumps_match(eng, ref, 24)


@pytest.mark.virtual_mesh
def test_scheduled_data_sharded_bit_exact(cfg, small_zipf):
    """schedule= composes with data_shards=: shard-local queues and
    block-diagonal permutations (no cross-device lane moves), still
    bit-exact per system."""
    _require_devices(2)
    from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

    arrays, ref = small_zipf
    eng = DataShardedPallasEngine(
        cfg, *arrays, data_shards=2, schedule=Schedule(), **_KW
    ).run()
    assert eng.occupancy.block_segments > 0
    assert _dumps_match(eng, ref, 24)


def test_batchjax_scheduled_with_faults_bit_exact(cfg):
    """XLA ensemble: chunk-barrier scheduling with streaming admission
    is bit-exact for dumps and fault counters even with an active
    fault model — each system's rng_key is seeded independently of its
    batch row, so fault streams survive row reassignment."""
    fcfg = dataclasses.replace(
        cfg, fault=FaultModel(drop=0.2, duplicate=0.1, reorder=0.2,
                              seed=7)
    )
    lens = heterogeneous_lengths(12, 24, dist="zipf", spread=4.0, seed=3)
    batch = [
        gen_uniform_random(fcfg, int(n), seed=100 + s)
        for s, n in enumerate(lens)
    ]
    from hpa2_tpu.ops.engine import BatchJaxEngine

    ref = BatchJaxEngine(fcfg, batch).run()
    eng = BatchJaxEngine(
        fcfg, batch, schedule=Schedule(resident=4, interval=64)
    ).run()
    assert eng.occupancy.admissions == 12 - 4
    assert _dumps_match(eng, ref, 12)
    assert eng.stats()["fault_retransmissions"] == (
        ref.stats()["fault_retransmissions"]
    )
    assert eng.stats()["fault_retransmissions"] > 0
    # crossed with the PR-5 host loop: the fused scan (the default
    # above) preserves fault streams bit-for-bit too
    eng5 = BatchJaxEngine(
        fcfg, batch,
        schedule=Schedule(resident=4, interval=64, fused=False),
    ).run()
    assert _dumps_match(eng, eng5, 12)
    assert eng.stats() == eng5.stats()
    assert eng5.occupancy.host_barriers == eng5.occupancy.intervals > 0


@pytest.mark.virtual_mesh
def test_batchjax_scheduled_data_sharded_bit_exact(cfg):
    _require_devices(2)
    lens = heterogeneous_lengths(12, 24, dist="zipf", spread=4.0, seed=3)
    batch = [
        gen_uniform_random(cfg, int(n), seed=100 + s)
        for s, n in enumerate(lens)
    ]
    from hpa2_tpu.ops.engine import BatchJaxEngine

    ref = BatchJaxEngine(cfg, batch).run()
    eng = BatchJaxEngine(
        cfg, batch, data_shards=2,
        schedule=Schedule(resident=4, interval=64),
    ).run()
    assert _dumps_match(eng, ref, 12)


# -- the fused scheduled path (ISSUE 6 tentpole) ---------------------------


def test_fused_vs_host_barrier_full_state_bit_exact(cfg, small_zipf):
    """The fused single-program run vs the PR-5 host-barrier loop at
    resident < batch: every carried state plane (incl. the Pallas
    scalars plane), every dump, and every occupancy counter except the
    launch accounting must be bit-identical."""
    arrays, ref = small_zipf
    eng = PallasEngine(
        cfg, *arrays, schedule=Schedule(resident=8), **_KW
    ).run()
    eng5 = PallasEngine(
        cfg, *arrays, schedule=Schedule(resident=8, fused=False), **_KW
    ).run()
    assert _dumps_match(eng, ref, 24)
    for f in eng.state:
        assert np.array_equal(
            np.asarray(eng.state[f]), np.asarray(eng5.state[f])
        ), f
    d, d5 = eng.occupancy.as_dict(), eng5.occupancy.as_dict()
    assert d["host_barriers"] == 0 and d["device_programs"] == 1
    assert d5["host_barriers"] == d5["intervals"] > 0
    strip = ("host_barriers", "device_programs")
    assert {k: v for k, v in d.items() if k not in strip} == (
        {k: v for k, v in d5.items() if k not in strip}
    )


def test_fused_single_device_program_jaxpr_guard(cfg, small_zipf):
    """The single-program pin: the fused runner's jaxpr holds exactly
    as many pallas_call kernels as ONE interval program (the scan body
    is traced once — no per-interval relaunch or duplication), and
    each kernel's op count equals the unscheduled program's kernel
    bit-for-bit (compaction/backfill confined to the barrier steps
    between scan iterations, outside the cycle loop)."""
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.analysis.ir import (
        count_eqns as _count_eqns, find_subjaxprs as _find_subjaxprs)
    from hpa2_tpu.ops import pallas_engine as pe
    from hpa2_tpu.ops.schedule import build_plan

    arrays, _ = small_zipf
    eng = PallasEngine(
        cfg, *arrays, schedule=Schedule(resident=8), **_KW
    )
    max_cycles = 10_000
    max_calls = max(1, -(-max_cycles // eng.cycles_per_call))
    plan = build_plan(
        eng._nseg, resident=eng._resident, block=eng.block, groups=1,
        threshold=eng.schedule.threshold,
    )
    assert plan.stats.intervals > 1  # a real multi-interval plan
    state = {
        f: jnp.asarray(v)
        for f, v in pe._init_state(
            cfg, eng._resident, snapshots=False
        ).items()
    }
    jx = jax.make_jaxpr(eng._fused_runner(max_cycles))(
        state, eng._tr_full, eng._tr_len_full,
        *eng._fused_plan_arrays(plan),
    )
    raw = pe._make_stream_run(
        cfg, eng._resident, eng.block, eng.cycles_per_call,
        eng._interpret, False, eng._window, 1, max_calls, frozenset(),
        True, False,
    )
    jxu = jax.make_jaxpr(raw)(
        state,
        jnp.zeros((cfg.num_procs, eng._window, eng._resident),
                  jnp.int32),
        jnp.zeros((cfg.num_procs, eng._resident), jnp.int32),
    )
    kf = _find_subjaxprs(jx.jaxpr, "pallas_call")
    ku = _find_subjaxprs(jxu.jaxpr, "pallas_call")
    assert len(ku) >= 1
    assert len(kf) == len(ku), (
        f"fused program holds {len(kf)} kernels vs {len(ku)} in one "
        f"interval — the scan body must be traced once, not per "
        f"interval"
    )
    assert [_count_eqns(k) for k in kf] == [_count_eqns(k) for k in ku]


@pytest.mark.virtual_mesh
def test_fused_data_sharded_vs_host_barrier_bit_exact(cfg, small_zipf):
    """Fused composes with data_shards=2 via shard-local plans (lanes
    never migrate across devices): state planes bit-identical to the
    PR-5 sharded loop, dumps bit-identical to the unsharded
    unscheduled reference."""
    _require_devices(2)
    from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

    arrays, ref = small_zipf
    eng = DataShardedPallasEngine(
        cfg, *arrays, data_shards=2, schedule=Schedule(), **_KW
    ).run()
    eng5 = DataShardedPallasEngine(
        cfg, *arrays, data_shards=2, schedule=Schedule(fused=False),
        **_KW
    ).run()
    assert _dumps_match(eng, ref, 24)
    for f in eng.state:
        assert np.array_equal(
            np.asarray(eng.state[f]), np.asarray(eng5.state[f])
        ), f
    assert eng.occupancy.device_programs == 1
    assert eng.occupancy.host_barriers == 0
    assert eng5.occupancy.host_barriers == eng5.occupancy.intervals > 0


def test_fused_batchjax_vs_host_barrier_bit_exact(cfg):
    """The XLA ensemble mirror: one lax.scan over admission waves vs
    the PR-5 chunk-barrier host loop — dumps and stats bit-exact, and
    the fused replay model fills the occupancy counters the host loop
    measured."""
    lens = heterogeneous_lengths(12, 24, dist="zipf", spread=4.0, seed=3)
    batch = [
        gen_uniform_random(cfg, int(n), seed=100 + s)
        for s, n in enumerate(lens)
    ]
    from hpa2_tpu.ops.engine import BatchJaxEngine

    eng = BatchJaxEngine(
        cfg, batch, schedule=Schedule(resident=4, interval=64)
    ).run()
    eng5 = BatchJaxEngine(
        cfg, batch, schedule=Schedule(resident=4, interval=64,
                                      fused=False)
    ).run()
    assert _dumps_match(eng, eng5, 12)
    assert eng.stats() == eng5.stats()
    assert eng.occupancy.device_programs == 1
    assert eng.occupancy.host_barriers == 0
    assert eng5.occupancy.host_barriers == eng5.occupancy.intervals > 0
    assert eng.occupancy.admissions == eng5.occupancy.admissions


# -- lane-permutation invariance (the property scheduling relies on) -------


def test_lane_permutation_invariance_both_engines(cfg, small_zipf):
    """Shuffling the ensemble lane order of an UNSCHEDULED run leaves
    every per-system final dump bit-identical on both ensemble
    engines — the independence property the scheduler's compaction
    permutations rest on."""
    arrays, ref = small_zipf
    perm = np.random.default_rng(5).permutation(24)
    shuf = tuple(a[perm] for a in arrays)
    eng = PallasEngine(cfg, *shuf, **_KW).run()
    for s in range(24):
        assert eng.system_final_dumps(s) == ref.system_final_dumps(
            int(perm[s])
        )

    from hpa2_tpu.ops.engine import BatchJaxEngine

    lens = heterogeneous_lengths(10, 20, dist="zipf", spread=4.0, seed=4)
    batch = [
        gen_uniform_random(cfg, int(n), seed=200 + s)
        for s, n in enumerate(lens)
    ]
    xref = BatchJaxEngine(cfg, batch).run()
    xperm = np.random.default_rng(6).permutation(10)
    xeng = BatchJaxEngine(cfg, [batch[i] for i in xperm]).run()
    for s in range(10):
        assert xeng.system_final_dumps(s) == xref.system_final_dumps(
            int(xperm[s])
        )


# -- admission policy (ISSUE 10 satellite) ---------------------------------


def test_policy_order_unit():
    from hpa2_tpu.ops.schedule import POLICIES, policy_order

    keys = np.array([3, 7, 7, 1])
    assert policy_order(keys, "fcfs").tolist() == [0, 1, 2, 3]
    # longest-first: descending remaining segments, stable among ties
    assert policy_order(keys, "longest-first").tolist() == [1, 2, 0, 3]
    with pytest.raises(ValueError, match="unknown policy"):
        policy_order(keys, "shortest-first")
    with pytest.raises(ValueError, match="unknown policy"):
        LaneScheduler(np.ones(8, np.int64), resident=4, block=4,
                      policy="bogus")
    assert set(POLICIES) == {
        "fcfs", "longest-first", "deadline-edf", "fair-drr"
    }

    # deadline-edf: ascending absolute deadline, -1 (no deadline) last,
    # stable among ties; without metadata it degrades to fcfs
    edf = policy_order(
        np.array([3, 7, 7]), "deadline-edf",
        deadline=np.array([5, -1, 2]),
    )
    assert edf.tolist() == [2, 0, 1]
    assert policy_order(np.array([3, 7]), "deadline-edf").tolist() == [0, 1]

    # fair-drr: weighted deficit round robin, deterministic.  Unit
    # costs, tenants 0/1 with weight 1:2 -> tenant 1 releases two jobs
    # per turn to tenant 0's one, arrival order within each tenant.
    drr = policy_order(
        np.ones(6, np.int64), "fair-drr",
        tenant=np.array([0, 0, 0, 1, 1, 1]),
        weights={0: 1.0, 1: 2.0},
    )
    assert drr.tolist() == [0, 3, 4, 1, 5, 2]
    with pytest.raises(ValueError, match="non-positive"):
        policy_order(
            np.ones(2, np.int64), "fair-drr",
            tenant=np.array([0, 1]), weights={1: 0.0},
        )


def test_longest_first_bit_exact_and_model_pinned(cfg, small_zipf):
    """``Schedule(policy="longest-first")`` reorders admission only —
    dumps stay bit-exact vs the unscheduled run, the static model
    replays the measured counters exactly, and on the skewed workload
    the policy never does worse than fcfs (it packs stragglers
    early)."""
    arrays, ref = small_zipf
    engs = {}
    for policy in ("fcfs", "longest-first"):
        eng = PallasEngine(
            cfg, *arrays,
            schedule=Schedule(resident=8, policy=policy), **_KW
        ).run()
        assert _dumps_match(eng, ref, 24)
        model = simulate(
            segments_needed(eng._tr_len_np, eng._window),
            resident=8, block=_KW["block"], groups=1,
            threshold=eng.schedule.threshold, policy=policy,
        )
        occ = eng.occupancy
        assert model.block_segments == occ.block_segments
        assert model.admissions == occ.admissions
        assert model.wait_intervals_max == occ.wait_intervals_max
        assert model.queue_depth_peak == occ.queue_depth_peak
        engs[policy] = occ
    assert (engs["longest-first"].block_segments
            <= engs["fcfs"].block_segments)


def test_service_policies_bit_exact_and_model_pinned(cfg, small_zipf):
    """The ISSUE-14 admission policies obey the same discipline as
    longest-first: admission reorder only (dumps bit-exact vs the
    unscheduled run) and the static model replays the measured
    counters exactly — including the new deadline outcome and
    per-tenant live-share counters."""
    arrays, ref = small_zipf
    b = 24
    deadlines = tuple((4, 12, -1)[s % 3] for s in range(b))
    tenants = tuple(s % 4 for s in range(b))
    weights = (1.0, 2.0, 4.0, 8.0)
    for policy in ("deadline-edf", "fair-drr"):
        eng = PallasEngine(
            cfg, *arrays,
            schedule=Schedule(
                resident=8, policy=policy, deadlines=deadlines,
                tenants=tenants, tenant_weights=weights,
            ),
            **_KW
        ).run()
        assert _dumps_match(eng, ref, b)
        model = simulate(
            segments_needed(eng._tr_len_np, eng._window),
            resident=8, block=_KW["block"], groups=1,
            threshold=eng.schedule.threshold, policy=policy,
            deadline=np.array(deadlines), tenant=np.array(tenants),
            tenant_weights=weights,
        )
        occ = eng.occupancy
        assert model.block_segments == occ.block_segments
        assert model.admissions == occ.admissions
        assert model.wait_intervals_max == occ.wait_intervals_max
        assert model.queue_depth_peak == occ.queue_depth_peak
        # the service counters replay exactly too
        assert model.deadline_met == occ.deadline_met
        assert model.deadline_missed == occ.deadline_missed
        assert (occ.deadline_met + occ.deadline_missed
                == sum(1 for d in deadlines if d >= 0))
        assert model.tenant_live == occ.tenant_live
        assert set(occ.tenant_live) == set(range(4))
        d = occ.as_dict()
        assert "deadline_hit_rate" in d and "tenant_share" in d


def test_queue_and_wait_counters(cfg, small_zipf):
    """The queue-depth / lane-wait serving counters: present in
    as_dict, zero when the whole ensemble is resident, active when the
    ensemble streams through a smaller residency."""
    arrays, ref = small_zipf
    full = PallasEngine(
        cfg, *arrays, schedule=Schedule(), **_KW
    ).run().occupancy.as_dict()
    assert full["queue_depth_peak"] == 0
    assert full["wait_intervals_mean"] == 0.0

    eng = PallasEngine(
        cfg, *arrays, schedule=Schedule(resident=8), **_KW
    ).run()
    d = eng.occupancy.as_dict()
    # 24 systems into 8 lanes: 16 queued at interval 0
    assert d["queue_depth_peak"] == 16
    assert 0 < d["queue_depth_mean"] <= 16
    assert d["wait_intervals_max"] >= d["wait_intervals_mean"] > 0
    st = eng.occupancy
    assert st.wait_intervals_total <= (
        st.wait_intervals_max * st.admissions
    )


def test_occupancy_cli_policy_column():
    from hpa2_tpu.analysis.occupancy import occupancy_table

    table, rc = occupancy_table(
        32, 48, 8, 8, spreads=(4.0,), policies=("fcfs", "longest-first")
    )
    assert rc == 0
    assert "longest-first" in table and "fcfs" in table
    assert "wait" in table
    # legacy policies leave the service columns blank ("-")
    assert "dlmiss" in table and "maxshr%" in table


def test_occupancy_cli_service_policy_columns():
    from hpa2_tpu.analysis.occupancy import occupancy_table

    table, rc = occupancy_table(
        32, 48, 8, 8, spreads=(4.0,),
        policies=("deadline-edf", "fair-drr"),
    )
    assert rc == 0
    assert "deadline-edf" in table and "fair-drr" in table
    # the deadline/tenant-aware policies fill the service columns with
    # real numbers: a max tenant share is always > 0
    rows = [r.split() for r in table.splitlines()[2:] if r.strip()]
    assert rows and all(float(r[-1]) > 0 for r in rows)


# -- heterogeneous workload generator --------------------------------------


def test_heterogeneous_lengths_properties():
    for dist in ("uniform", "zipf"):
        lens = heterogeneous_lengths(64, 96, dist=dist, spread=8.0,
                                     seed=0)
        assert lens.shape == (64,)
        assert lens.min() >= max(1, round(96 / 8.0))
        assert lens.max() == 96  # one system pinned to the max
    with pytest.raises(ValueError):
        heterogeneous_lengths(8, 16, dist="bimodal")
    with pytest.raises(ValueError):
        heterogeneous_lengths(8, 16, spread=0.5)


def test_occupancy_cli_table():
    from hpa2_tpu.analysis.occupancy import occupancy_table

    table, rc = occupancy_table(32, 48, 8, 8, spreads=(4.0, 8.0))
    assert rc == 0
    assert "lockstep" in table and "zipf" in table
    assert "barrier" in table and "progrm" in table
    # fused launch accounting: 0 barriers / 1 program on every row
    # (the last two columns are the ISSUE-14 service columns, "-" for
    # the legacy policies)
    for row in table.splitlines()[2:]:
        assert row.split()[-4:-2] == ["0", "1"]
        assert row.split()[-2:] == ["-", "-"]
    # the PR-5 host loop pays one of each per interval
    t5, rc5 = occupancy_table(32, 48, 8, 8, spreads=(4.0,), fused=False)
    assert rc5 == 0
    barrier, program = t5.splitlines()[2].split()[-4:-2]
    assert barrier == program and int(barrier) > 1


def test_predicted_stats_launch_accounting():
    """Satellite pin: the model reports exactly 1 device program on
    the fused path where the PR-5 path reports n_intervals."""
    from hpa2_tpu.analysis.occupancy import predicted_stats

    lens = heterogeneous_lengths(16, 32, dist="zipf", spread=4.0, seed=0)
    fused = predicted_stats(lens, 8, 4, resident=8)
    host = predicted_stats(lens, 8, 4, resident=8, fused=False)
    assert fused.intervals == host.intervals > 1
    assert fused.host_barriers == 0 and fused.device_programs == 1
    assert host.host_barriers == host.intervals
    assert host.device_programs == host.intervals
