"""Test harness config.

All JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
is exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench runs on the real chip).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pathlib

import pytest

# persistent XLA compilation cache: the jitted step/run programs are
# identical across test runs, so recompiles dominate otherwise
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/hpa2_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
REFERENCE_TESTS = pathlib.Path("/root/reference/tests")


@pytest.fixture(scope="session")
def reference_tests_dir():
    if not REFERENCE_TESTS.is_dir():
        pytest.skip("reference test corpus not available")
    return REFERENCE_TESTS
