"""Test harness config.

All JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
is exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench runs on the real chip).
"""

import os
import sys


def pytest_configure(config):
    # The axon sitecustomize registers the TPU PJRT plugin at
    # interpreter startup and pins the backend, so an in-process
    # JAX_PLATFORMS override is too late — re-exec once with a clean
    # environment to get the virtual 8-device CPU mesh.  Capture must
    # be released first or the child writes into pytest's temp file.
    if os.environ.get("_HPA2_TEST_REEXEC") == "1":
        return
    env = dict(os.environ)
    env["_HPA2_TEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable axon TPU registration
    xla_flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        env["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    args = list(config.invocation_params.args)
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + args, env)

import pathlib

import pytest

# persistent XLA compilation cache: the jitted step/run programs are
# identical across test runs, so recompiles dominate otherwise
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/hpa2_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
REFERENCE_TESTS = pathlib.Path("/root/reference/tests")


@pytest.fixture(scope="session")
def reference_tests_dir():
    if not REFERENCE_TESTS.is_dir():
        pytest.skip("reference test corpus not available")
    return REFERENCE_TESTS
