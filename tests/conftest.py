"""Test harness config.

All JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
is exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench runs on the real chip).
"""

import os
import sys


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "sweep: randomized cross-engine differential sweep "
        "(tests/test_random_differential.py)",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); e.g. the "
        "TSan bench in tests/test_sanitizers.py",
    )
    config.addinivalue_line(
        "markers",
        "virtual_mesh: needs the 8-device virtual CPU mesh "
        "(XLA_FLAGS=--xla_force_host_platform_device_count=8); skipped "
        "cleanly when the flag could not take effect, e.g. jax was "
        "initialized before it was set",
    )
    # The axon sitecustomize registers the TPU PJRT plugin at
    # interpreter startup and pins the backend, so an in-process
    # JAX_PLATFORMS override is too late — re-exec once with a clean
    # environment to get the virtual 8-device CPU mesh.  Capture must
    # be released first or the child writes into pytest's temp file.
    if os.environ.get("_HPA2_TEST_REEXEC") == "1":
        return
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:  # bare `pytest` puts only tests/ on path
        sys.path.insert(0, repo_root)
    from hpa2_tpu.hostenv import forced_cpu_env, has_device_count_flag

    env = forced_cpu_env(
        n_devices=None if has_device_count_flag() else 8
    )
    env["_HPA2_TEST_REEXEC"] = "1"
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    args = list(config.invocation_params.args)
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + args, env)

import pathlib

import pytest

# (the persistent XLA compile cache is configured via the re-exec env:
# hostenv.cache_env sets JAX_COMPILATION_CACHE_DIR and the min-compile
# threshold, which jax reads at import)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
REFERENCE_TESTS = pathlib.Path("/root/reference/tests")


@pytest.fixture(scope="session")
def reference_tests_dir():
    if not REFERENCE_TESTS.is_dir():
        pytest.skip("reference test corpus not available")
    return REFERENCE_TESTS


def pytest_collection_modifyitems(config, items):
    """``virtual_mesh``-marked tests skip cleanly when the 8-device
    mesh is unavailable — the device-count XLA flag cannot take effect
    once jax has initialized its backend (e.g. a stale interpreter, or
    a host that pinned XLA_FLAGS to something else)."""
    if not any(i.get_closest_marker("virtual_mesh") for i in items):
        return
    import jax

    n = len(jax.devices())
    if n >= 8:
        return
    skip = pytest.mark.skip(
        reason=f"virtual 8-device mesh unavailable ({n} device(s); the "
        "device-count flag did not take effect)"
    )
    for item in items:
        if item.get_closest_marker("virtual_mesh"):
            item.add_marker(skip)
