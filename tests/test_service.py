"""Multi-tenant service plane (``hpa2_tpu.service``): the framed wire
protocol, credit-based admission, and the wire frontend.

The contract under test (PERF.md "Multi-tenant service plane"):

1. **Framing is transport-independent** — frames reassemble
   identically from any byte segmentation (byte-at-a-time included),
   and framing violations raise loudly.
2. **Backpressure is loud** — over-submitting past the connection's
   admission credits draws a NACK with a reason; nothing hangs and
   nothing is silently dropped.  Duplicate ids and malformed records
   NACK too.
3. **Admission order is the ack transcript** — the ACK ``seq`` is the
   global admission sequence; the serving loop admits in seq order,
   so two concurrent clients get a deterministic schedule fixed by
   their acks, not by reader-thread timing — and the served dumps are
   byte-identical to a one-shot run of the seq-ordered ensemble.
"""

import threading

import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.service import (
    ACK,
    BYE,
    DEADLINE_CLASSES,
    NACK,
    RESULT,
    SUBMIT,
    AdmissionLedger,
    AdmissionReject,
    FrameReader,
    TenantTable,
    WireClient,
    WireError,
    WireJobSource,
    WireNack,
    encode_frame,
    resolve_deadline,
)
from hpa2_tpu.serving import job_to_record, serve, synthetic_jobs

ROBUST = Semantics().robust()


@pytest.fixture(scope="module")
def cfg():
    return SystemConfig(num_procs=4, semantics=ROBUST)


@pytest.fixture(scope="module")
def jobs(cfg):
    return synthetic_jobs(cfg, 8, 24, seed=7, spread=3.0)


def _records(jobs, tenant_of=lambda i: ""):
    recs = []
    for i, j in enumerate(jobs):
        r = job_to_record(j)
        t = tenant_of(i)
        if t:
            r["tenant"] = t
        recs.append(r)
    return recs


# -- framing ----------------------------------------------------------------


def test_frame_roundtrip_byte_at_a_time():
    frames = [
        (SUBMIT, {"id": "j0", "traces": [[["R", 1]]]}),
        (ACK, {"id": "j0", "seq": 0, "queue_pos": 0}),
        (RESULT, {"id": "j0", "latency_s": 0.25}),
        (BYE, {}),
    ]
    blob = b"".join(encode_frame(t, p) for t, p in frames)
    reader = FrameReader()
    got = []
    for i in range(len(blob)):
        got.extend(reader.feed(blob[i:i + 1]))
    assert [(f.ftype, f.payload) for f in got] == frames
    # and in one shot — segmentation never matters
    got2 = FrameReader().feed(blob)
    assert [(f.ftype, f.payload) for f in got2] == frames


def test_framing_violations_are_loud():
    with pytest.raises(WireError, match="bad magic"):
        FrameReader().feed(b"\x00" * 8)
    good = encode_frame(BYE)
    bad_version = bytes([good[0], 99]) + good[2:]
    with pytest.raises(WireError, match="version"):
        FrameReader().feed(bad_version)
    bad_type = bytes([good[0], good[1], 200]) + good[3:]
    with pytest.raises(WireError, match="unknown frame type"):
        FrameReader().feed(bad_type)
    with pytest.raises(WireError, match="unknown frame type"):
        encode_frame(200, {})


# -- tenants + deadline classes ---------------------------------------------


def test_tenant_table_parse():
    t = TenantTable.parse("alice:4, bob:1.5")
    assert t.weight_of("alice") == 4.0
    assert t.weight_of("bob") == 1.5
    assert t.weight_of("unlisted") == 1.0
    assert not TenantTable.parse("")
    with pytest.raises(ValueError, match="name:weight"):
        TenantTable.parse("alice")
    with pytest.raises(ValueError, match="name:weight"):
        TenantTable.parse("alice:heavy")
    with pytest.raises(ValueError, match="> 0"):
        TenantTable.parse("alice:0")


def test_resolve_deadline_classes():
    assert resolve_deadline({}) == -1
    assert resolve_deadline({"deadline": 5}) == 5
    for name, dl in DEADLINE_CLASSES.items():
        assert resolve_deadline({"class": name}) == dl
    # an explicit deadline always wins over the class
    assert resolve_deadline({"class": "interactive", "deadline": 99}) == 99
    with pytest.raises(ValueError, match="unknown deadline class"):
        resolve_deadline({"class": "platinum"})


# -- the admission ledger ---------------------------------------------------


def test_ledger_credits_duplicates_and_seq_order():
    led = AdmissionLedger(credits=2)
    assert led.register(0) == 2
    assert led.try_submit(0, {"id": "a", "traces": []}) == (0, 0)
    assert led.try_submit(0, {"id": "b", "traces": []}) == (1, 1)
    with pytest.raises(AdmissionReject, match="backpressure"):
        led.try_submit(0, {"id": "c", "traces": []})
    with pytest.raises(AdmissionReject, match="'id'"):
        led.try_submit(0, {"traces": []})
    with pytest.raises(AdmissionReject, match="exactly one"):
        led.try_submit(0, {"id": "x"})
    wave, back = led.take_wave()
    assert [p.seq for p in wave] == [0, 1]
    assert back == {0: 2}
    # credits came back: submitting works again, duplicates never do
    assert led.try_submit(0, {"id": "c", "traces": []})[0] == 2
    with pytest.raises(AdmissionReject, match="duplicate"):
        led.try_submit(0, {"id": "a", "traces": []})
    assert led.pending == 1


# -- credit backpressure over the wire --------------------------------------


def test_over_submit_draws_nack_then_drains(cfg, jobs):
    """The credit guard: with the serving loop NOT yet draining, the
    (credits+1)-th submit must draw a backpressure NACK — loudly,
    deterministically, with no hang — and the ack'd jobs still serve
    to completion afterwards."""
    recs = _records(jobs)
    src = WireJobSource(cfg, credits=2)
    cli = WireClient(*src.address)
    assert cli.credits == 2
    acks = [cli.submit(recs[0]), cli.submit(recs[1])]
    assert [a["seq"] for a in acks] == [0, 1]
    with pytest.raises(WireNack, match="backpressure"):
        cli.submit(recs[2], force=True)
    # and again: backpressure NACKs are repeatable, never a hang
    with pytest.raises(WireNack, match="backpressure"):
        cli.submit(recs[2], force=True)

    streamed = []
    t = threading.Thread(
        target=lambda: streamed.extend(cli.finish()), daemon=True
    )
    t.start()
    results, stats = serve(
        cfg, src, backend="pallas", resident=4, window=8, block=4,
        emit=src.deliver,
    )
    t.join(timeout=30)
    cli.close()
    assert sorted(r.job_id for r in results) == sorted(
        r["id"] for r in recs[:2]
    )
    assert sorted(r["id"] for r in streamed) == sorted(
        r["id"] for r in recs[:2]
    )
    # the drained wave replenished the client's credits
    assert cli.credits == 2


def test_credit_replenishment_self_clocks(cfg, jobs):
    """A client holding fewer credits than jobs still pushes the whole
    feed through: submit() blocks on CREDIT frames as the scheduler
    drains waves — backpressure clocks the client, drops nothing."""
    recs = _records(jobs)
    src = WireJobSource(cfg, credits=2)
    streamed, acks = [], []

    def client():
        with WireClient(*src.address) as cli:
            for r in recs:
                acks.append(cli.submit(r))
            streamed.extend(cli.finish())

    t = threading.Thread(target=client, daemon=True)
    t.start()
    results, _ = serve(
        cfg, src, backend="pallas", resident=4, window=8, block=4,
        emit=src.deliver,
    )
    t.join(timeout=30)
    assert [a["seq"] for a in acks] == list(range(len(recs)))
    assert sorted(r["id"] for r in streamed) == sorted(
        r["id"] for r in recs
    )
    assert len(results) == len(recs)


# -- deterministic two-client admission -------------------------------------


def test_two_clients_admission_order_is_ack_order(cfg, jobs):
    """Two clients submitting concurrently: whatever interleaving the
    reader threads saw, the scheduler admits in ACK-seq order, and the
    served dumps are byte-identical to a one-shot run of the ensemble
    ordered by seq."""
    from hpa2_tpu.ops.pallas_engine import PallasLaneSession
    from hpa2_tpu.serving.loop import ServingSession

    recs = _records(jobs, tenant_of=lambda i: ("a", "b")[i % 2])
    half = len(recs) // 2
    src = WireJobSource(cfg, credits=16)
    acks = {}

    def client(mine):
        with WireClient(*src.address) as cli:
            for r in mine:
                acks[r["id"]] = cli.submit(r)
            cli.finish()

    ts = [threading.Thread(target=client, args=(recs[:half],)),
          threading.Thread(target=client, args=(recs[half:],))]
    for t in ts:
        t.start()

    sess = PallasLaneSession(cfg, 4, 8, block=4)
    drv = ServingSession(sess, src, emit=src.deliver)
    results, stats = drv.run()
    for t in ts:
        t.join(timeout=30)

    assert len(acks) == len(recs)
    seqs = sorted(acks.values(), key=lambda a: a["seq"])
    assert [a["seq"] for a in seqs] == list(range(len(recs)))
    # system ids are assigned in poll order == seq order
    assert [j.job_id for j in drv._jobs] == [a["id"] for a in seqs]

    # one-shot reference over the seq-ordered ensemble: byte-identical
    by_id = {j.job_id: j for j in jobs}
    ordered = [by_id[a["id"]] for a in seqs]
    ref = PallasEngine(
        cfg,
        np.stack([j.tr_op for j in ordered]),
        np.stack([j.tr_addr for j in ordered]),
        np.stack([j.tr_val for j in ordered]),
        np.stack([j.tr_len for j in ordered]),
        block=4, trace_window=8, snapshots=False,
        schedule=Schedule(resident=4, fused=False),
    ).run()
    got = {r.job_id: r.dumps for r in results}
    for s, j in enumerate(ordered):
        assert got[j.job_id] == ref.system_final_dumps(s), j.job_id
    assert all(c == 1 for c in stats.compile_counts.values())


# -- post-ack rejection stays loud ------------------------------------------


def test_malformed_trace_body_nacks_after_ack(cfg):
    """A record that passes the ledger's shape checks but fails job
    parsing (bad instruction body) must NACK at poll time — a post-ack
    rejection, never a silent drop."""
    src = WireJobSource(cfg, credits=4)
    cli = WireClient(*src.address)
    bad = {"id": "bad", "traces": [[["Q", 1]]] + [[]] * 3}
    ack = cli.submit(bad)
    assert ack["seq"] == 0
    assert src.poll() == []  # the wave rejected the only record
    fr = cli._next_frame((NACK,))
    assert "bad instruction" in fr.payload["reason"]
    cli.close()
    src.close()
