"""Packed state planes (``packed=True`` on the Pallas engine).

The cycle body computes in int32 either way — packing is purely a
storage-layout change (cachew -> cvalw u8 + cmetaw u8/u16, dirw ->
dmemw u8 + dmetaw u8/u16) with all promotion funneled through the
sanctioned ``_widen*``/``_narrow*`` helpers — so every run mode must
stay bit-exact against the unpacked layout: unscheduled, snapshots,
the fused scheduled path, and split-sharer-plane geometries.  The AST
lint enforces the funnel statically (dtype-widening rule)."""

import os

import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import (
    PallasEngine,
    _join_word_planes_np,
    _split_word_planes_np,
    packed_plane_dtypes,
    state_dtypes,
)
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.utils.trace import (
    gen_heterogeneous_random_arrays,
    gen_uniform_random_arrays,
)

ROBUST = Semantics().robust()

_KW = dict(block=4, cycles_per_call=32, trace_window=8, gate=True)


@pytest.fixture(scope="module")
def cfg():
    return SystemConfig(num_procs=4, semantics=ROBUST)


def _planes_match(peng, ueng):
    """Packed engine vs unpacked engine: rebuild the legacy words from
    the split planes and compare everything else directly."""
    joined = dict(ueng.state)
    for f in ueng.state:
        if f in ("cachew", "dirw", "snap_cachew", "snap_dirw"):
            continue
        if not np.array_equal(
            np.asarray(peng.state[f]), np.asarray(joined[f])
        ):
            return False
    for prefix in ("", "snap_"):
        if f"{prefix}cachew" not in ueng.state:
            continue
        cw, dw = _join_word_planes_np(
            np.asarray(peng.state[f"{prefix}cvalw"]),
            np.asarray(peng.state[f"{prefix}cmetaw"]),
            np.asarray(peng.state[f"{prefix}dmemw"]),
            np.asarray(peng.state[f"{prefix}dmetaw"]),
        )
        if not np.array_equal(cw, np.asarray(ueng.state[f"{prefix}cachew"])):
            return False
        if not np.array_equal(dw, np.asarray(ueng.state[f"{prefix}dirw"])):
            return False
    return True


# -- layout ---------------------------------------------------------------


def test_packed_dtypes_by_geometry():
    small = SystemConfig(num_procs=4, cache_size=2, mem_size=8,
                         semantics=ROBUST)  # 32 addresses: meta fits u8
    dt = packed_plane_dtypes(small)
    assert dt["cvalw"] == np.uint8 and dt["dmemw"] == np.uint8
    assert dt["cmetaw"] == np.uint8 and dt["dmetaw"] == np.uint8

    wide = SystemConfig(num_procs=4, cache_size=4, mem_size=64,
                        msg_buffer_size=4, semantics=ROBUST)  # 256 addrs
    assert packed_plane_dtypes(wide)["cmetaw"] == np.uint16

    split = SystemConfig(num_procs=22, cache_size=2, mem_size=4,
                         msg_buffer_size=16, semantics=ROBUST)
    # split mode: sharers live in dirs{w} planes, dmetaw is state-only
    assert packed_plane_dtypes(split)["dmetaw"] == np.uint8


def test_unpackable_geometry_raises():
    huge = SystemConfig(num_procs=4, mem_size=4096, semantics=ROBUST)
    with pytest.raises(ValueError, match="packed"):
        packed_plane_dtypes(huge)
    with pytest.raises(ValueError, match="packed"):
        PallasEngine(
            huge, *gen_uniform_random_arrays(huge, 4, 8, seed=0),
            packed=True, **_KW
        )


def test_state_dtypes_cover_snap_twins(cfg):
    dt = state_dtypes(cfg, snapshots=True, packed=True)
    for f in ("cvalw", "cmetaw", "dmemw", "dmetaw"):
        assert dt[f] == dt[f"snap_{f}"]
        assert dt[f].itemsize < 4
    assert dt["scalars"] == np.int32  # everything else stays i32


def test_split_join_roundtrip_lossless(cfg):
    rng = np.random.default_rng(0)
    c, m = cfg.cache_size, cfg.mem_size
    # exercise the full field ranges, incl. the empty (addr+1 == 0) tag
    cachew = (
        rng.integers(0, 4, (4, c, 16))
        | (rng.integers(0, 256, (4, c, 16)) << 2)
        | (rng.integers(0, cfg.num_addresses + 1, (4, c, 16)) << 10)
    ).astype(np.int32)
    dirw = (
        rng.integers(0, 256, (4, m, 16))
        | (rng.integers(0, 4, (4, m, 16)) << 8)
        | (rng.integers(0, 1 << cfg.num_procs, (4, m, 16)) << 10)
    ).astype(np.int32)
    planes = _split_word_planes_np(cfg, cachew, dirw)
    cw, dw = _join_word_planes_np(
        planes["cvalw"], planes["cmetaw"], planes["dmemw"],
        planes["dmetaw"],
    )
    assert np.array_equal(cw, cachew)
    assert np.array_equal(dw, dirw)


# -- bit-exactness --------------------------------------------------------


def test_packed_bit_exact_with_snapshots(cfg):
    arrays = gen_heterogeneous_random_arrays(
        cfg, 8, 24, dist="zipf", spread=4.0, seed=2
    )
    # snapshots require a single-segment window (>= the longest trace)
    kw = {**_KW, "trace_window": 24}
    ueng = PallasEngine(cfg, *arrays, snapshots=True, **kw).run()
    peng = PallasEngine(
        cfg, *arrays, snapshots=True, packed=True, **kw
    ).run()
    assert _planes_match(peng, ueng)
    for s in range(8):
        assert peng.system_final_dumps(s) == ueng.system_final_dumps(s)
        assert peng.system_snapshots(s) == ueng.system_snapshots(s)


def test_packed_fused_scheduled_bit_exact(cfg):
    arrays = gen_heterogeneous_random_arrays(
        cfg, 24, 32, dist="zipf", spread=4.0, seed=1
    )
    ref = PallasEngine(cfg, *arrays, snapshots=False, **_KW).run()
    eng = PallasEngine(
        cfg, *arrays, snapshots=False, packed=True,
        schedule=Schedule(resident=8), **_KW
    ).run()
    assert eng.occupancy.device_programs == 1
    for s in range(24):
        assert eng.system_final_dumps(s) == ref.system_final_dumps(s)
    assert np.array_equal(
        np.asarray(eng.state["scalars"]), np.asarray(ref.state["scalars"])
    )


def test_packed_split_plane_22_nodes_bit_exact():
    cfg = SystemConfig(num_procs=22, cache_size=2, mem_size=4,
                       msg_buffer_size=16, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 2, 12, seed=4)
    kw = dict(block=2, cycles_per_call=32, interpret=True,
              snapshots=False, trace_window=5, gate=False)
    ueng = PallasEngine(cfg, *arrays, **kw).run(max_cycles=400_000)
    peng = PallasEngine(
        cfg, *arrays, packed=True, **kw
    ).run(max_cycles=400_000)
    assert _planes_match(peng, ueng)
    for b in range(2):
        assert peng.system_final_dumps(b) == ueng.system_final_dumps(b)


# -- the lint funnel ------------------------------------------------------


def test_lint_dtype_widening_rule(tmp_path):
    from hpa2_tpu.analysis.lint import run_lint

    ops = tmp_path / "hpa2_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "bad.py").write_text(
        "def _widen_cache(cvalw, cmetaw):\n"
        "    return (cmetaw >> 2) | cvalw   # sanctioned: not flagged\n"
        "def kernel(s):\n"
        "    a = s['cmetaw'] + 1            # arithmetic: flagged\n"
        "    b = s['dmemw'] > 0             # comparison: flagged\n"
        "    c = s['cvalw'].astype('int32') # stray astype: flagged\n"
        "    d = s['cvalw'][0]              # structural: not flagged\n"
        "    e = _widen_cache(s['cvalw'], s['cmetaw'])  # not flagged\n"
        "    return a, b, c, d, e\n"
    )
    findings = run_lint(
        str(tmp_path), [os.path.join("hpa2_tpu", "ops", "bad.py")]
    )
    widening = [f for f in findings if f.rule == "dtype-widening"]
    assert sorted(f.line for f in widening) == [4, 5, 6]


def test_lint_clean_on_repo():
    # the real kernel code funnels every promotion through the
    # sanctioned helpers — the rule must be zero-finding on it
    from hpa2_tpu.analysis.lint import lint_file

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_file(
        repo, os.path.join("hpa2_tpu", "ops", "pallas_engine.py")
    )
    assert [f for f in findings if f.rule == "dtype-widening"] == []
