"""Interconnect topology model (hpa2_tpu/interconnect/).

Gates for the deterministic contention model:

  (1) registry — compiled topologies have the advertised shapes,
      routing (XY columns-first, torus shorter-way wraps, two-tier
      hierarchical), and validation;
  (2) a hand-computed mesh2d case pinning EXACT delivery cycles out
      of the sequential LinkTracker reference, variant by variant;
  (3) ``topology="ideal"`` is byte-identical to the pre-topology
      engines in every mode (plain, fused/batched, packed Pallas,
      data-sharded, faulty) even when the rest of the interconnect
      config differs;
  (4) spec <-> JAX agreement (dumps, cycles, counters, per-link
      stats) under contention, multicast, and combining;
  (5) the one-stats-schema pin: fault/topology counters appear only
      when nonzero, and fault delay/retransmission counts surface in
      engine stats and the StallDiagnostic;
  (6) checkpoint round-trips carry the ``deliver_at`` lane;
  (7) backends without a topology implementation refuse non-ideal
      configs loudly (Pallas, node-sharded, replay, CLI);
  (8) the interconnect-purity lint rule fires on RNG/clock imports
      and the repo itself is clean.
"""

import dataclasses
import os

import numpy as np
import pytest

from hpa2_tpu.config import (
    FaultModel,
    InterconnectConfig,
    Semantics,
    SystemConfig,
)
from hpa2_tpu.interconnect import LinkTracker, TOPOLOGIES, build_topology
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.ops.engine import BatchJaxEngine, JaxEngine, stall_diagnostic
from hpa2_tpu.utils.trace import (
    gen_uniform_random,
    gen_uniform_random_arrays,
)

ROBUST = Semantics().robust()


def _dumps_equal(a, b):
    return [dataclasses.asdict(x) for x in a] == [
        dataclasses.asdict(y) for y in b
    ]


def _stats_agree(a, b):
    # zero-tolerant: the spec omits never-incremented keys, the device
    # schema always carries the core counters (test_observability.py).
    # elided_cycles/multi_hit_retired describe how the device *executed*
    # (event-driven fast-forwards), not what was simulated — the
    # lockstep spec engine can never report them, and hop latency opens
    # quiet in-flight gaps that make them nonzero even on uniform
    # traces, so they are excluded from semantic parity here
    for key in set(a) | set(b):
        if key in ("elided_cycles", "multi_hit_retired"):
            continue
        assert a.get(key, 0) == b.get(key, 0), key


def _mesh_cfg(topo="mesh2d", procs=8, **kw):
    return SystemConfig(
        num_procs=procs,
        max_instr_num=0,
        semantics=ROBUST,
        interconnect=InterconnectConfig(topology=topo, **kw),
    )


# -- (1) topology registry ------------------------------------------------


@pytest.mark.parametrize("name", ["mesh2d", "torus2d", "hierarchical"])
@pytest.mark.parametrize("n", [4, 8])
def test_registry_shapes(name, n):
    t = build_topology(name, n)
    L = t.num_links
    assert t.path_mat.shape == (n, n, L)
    assert t.hops.shape == (n, n) and t.base_lat.shape == (n, n)
    assert np.array_equal(np.diag(t.hops), np.zeros(n))
    assert np.array_equal(np.diag(t.base_lat), np.zeros(n))
    # path incidence is consistent: row sums == hop counts, and with
    # hop_latency=1 grids the base latency equals the hop count
    assert np.array_equal(t.path_mat.sum(axis=2), t.hops)
    if name != "hierarchical":
        assert np.array_equal(t.base_lat, t.hops)
    # routed paths are direction-symmetric in length
    assert np.array_equal(t.hops, t.hops.T)


def test_mesh2d_2x2_routing():
    # 2x2 grid: 0 1 / 2 3; XY routing goes columns first, then rows
    t = build_topology("mesh2d", 4)
    assert t.num_links == 8  # 4 undirected edges, one link per direction
    i01 = t.link_names.index("n0->n1")
    i13 = t.link_names.index("n1->n3")
    assert t.base_lat[0, 3] == 2
    assert t.path_mat[0, 3, i01] and t.path_mat[0, 3, i13]
    assert t.path_mat[0, 3].sum() == 2


def test_torus_wraps_the_shorter_way():
    # 1x3 ring: 0 -> 2 is one hop backwards on the torus, two on the mesh
    assert build_topology("torus2d", 3).hops[0, 2] == 1
    assert build_topology("mesh2d", 3).hops[0, 2] == 2
    # 4x4 torus: distance 2 along a row is a tie; ties break positive
    t = build_topology("torus2d", 16)
    assert t.path_mat[0, 2, t.link_names.index("n0->n1")]
    assert not t.path_mat[0, 2, t.link_names.index("n0->n3")]


def test_hierarchical_two_tier():
    # n=8 -> 2 groups of 4: up/down links per node + 2 switch links
    t = build_topology("hierarchical", 8)
    assert t.num_links == 8 * 2 + 2
    assert t.base_lat[0, 1] == 2        # n0->s0, s0->n1
    assert t.base_lat[0, 7] == 1 + 4 + 1  # DCN tier costs 4x
    assert t.hops[0, 7] == 3


def test_build_topology_validation_and_cache():
    with pytest.raises(ValueError, match="unknown topology"):
        build_topology("ring", 4)
    with pytest.raises(ValueError, match="n >= 1"):
        build_topology("mesh2d", 0)
    with pytest.raises(ValueError, match="hop_latency"):
        build_topology("mesh2d", 4, hop_latency=0)
    assert build_topology("ideal", 4).num_links == 0
    # cached: jit caches key on config, so tensor identity matters
    assert build_topology("mesh2d", 8) is build_topology("mesh2d", 8)


# -- (2) hand-computed mesh2d delivery cycles -----------------------------


def _accept(tr, cycle, s, d, inv=False, read=False, addr=0):
    return tr.on_accept(cycle, s, d, 0, addr, inv, read)


def test_linktracker_mesh2d_hand_computed():
    """2x2 mesh, bandwidth 1: four messages accepted in walk order in
    cycle 10.  Paths: 0->3 = [n0->n1, n1->n3], 1->3 = [n1->n3],
    2->3 = [n2->n3]."""
    t = build_topology("mesh2d", 4)
    tr = LinkTracker(t)
    tr.begin_cycle()
    # empty links: delay is the pure base latency
    assert _accept(tr, 10, 0, 3) == 12          # base 2, penalty 0
    # n1->n3 already carries one message -> queues one cycle behind it
    assert _accept(tr, 10, 1, 3) == 12          # base 1, penalty 1
    assert _accept(tr, 10, 2, 3) == 11          # untouched link
    # second 0->3: one prior on n0->n1, two prior on n1->n3
    assert _accept(tr, 10, 0, 3) == 15          # base 2, penalty 1+2
    tr.end_cycle()
    assert tr.n_topo_delay == (2 - 1) + (2 - 1) + (1 - 1) + (5 - 1)
    assert int(tr.max_load[t.link_names.index("n1->n3")]) == 3
    assert int(tr.traversals[t.link_names.index("n0->n1")]) == 2


def test_linktracker_bandwidth_absorbs_contention():
    tr = LinkTracker(build_topology("mesh2d", 4), bandwidth=2)
    tr.begin_cycle()
    assert _accept(tr, 10, 1, 3) == 11
    # one prior traversal // bw 2 = 0 extra cycles
    assert _accept(tr, 10, 1, 3) == 11
    assert _accept(tr, 10, 1, 3) == 12          # 2 // 2 = 1
    tr.end_cycle()


def test_linktracker_multicast_shares_links():
    """INV fan-out from node 0 to 1, 2, 3: the 0->3 leg rides the
    already-traversed n0->n1 link (saved) but still queues behind the
    group's single transfer on it."""
    t = build_topology("mesh2d", 4)
    tr = LinkTracker(t, multicast=True)
    tr.begin_cycle()
    assert _accept(tr, 10, 0, 1, inv=True, addr=5) == 11
    assert _accept(tr, 10, 0, 2, inv=True, addr=5) == 11
    assert _accept(tr, 10, 0, 3, inv=True, addr=5) == 13  # base 2 + 1
    tr.end_cycle()
    assert tr.n_multicast_saved == 1
    assert int(tr.traversals[t.link_names.index("n0->n1")]) == 1


def test_linktracker_combining_merges_reads():
    t = build_topology("mesh2d", 4)
    tr = LinkTracker(t, combining=True)
    tr.begin_cycle()
    assert _accept(tr, 10, 1, 0, read=True, addr=9) == 11
    # same-address read merges: zero occupancy contribution, still
    # delivered at its own base latency (3->0 = [n3->n2, n2->n0])
    assert _accept(tr, 10, 3, 0, read=True, addr=9) == 12
    tr.end_cycle()
    assert tr.n_combined == 1
    assert int(tr.traversals.sum()) == 1        # only the first read


# -- config surface -------------------------------------------------------


def test_interconnect_config_validation():
    with pytest.raises(ValueError, match="unknown topology"):
        InterconnectConfig(topology="ring")
    with pytest.raises(ValueError, match="non-ideal"):
        InterconnectConfig(topology="ideal", multicast=True)
    with pytest.raises(ValueError, match="link_bandwidth"):
        InterconnectConfig(topology="mesh2d", link_bandwidth=0)
    assert not InterconnectConfig().enabled
    assert InterconnectConfig(topology="mesh2d").enabled


def test_legacy_fault_alias_folds_into_interconnect():
    # SystemConfig(fault=...) is the deprecated spelling of
    # SystemConfig(interconnect=InterconnectConfig(fault=...))
    legacy = SystemConfig(fault=FaultModel(drop=0.5, seed=3))
    assert legacy.interconnect.fault.drop == 0.5
    assert legacy.fault == legacy.interconnect.fault
    nested = SystemConfig(
        interconnect=InterconnectConfig(fault=FaultModel(drop=0.5, seed=3))
    )
    assert legacy.interconnect == nested.interconnect
    with pytest.raises(ValueError, match="both"):
        SystemConfig(
            fault=FaultModel(drop=0.5),
            interconnect=InterconnectConfig(fault=FaultModel(drop=0.25)),
        )


# -- (3) ideal is byte-identical to the pre-topology engines --------------

# a distinct config object that still takes the ideal path: every
# other interconnect knob must be inert when topology == "ideal"
_IDEAL_VARIANT = InterconnectConfig(
    topology="ideal", hop_latency=7, link_bandwidth=3
)


def test_ideal_byte_identity_plain():
    cfg = SystemConfig(num_procs=8, max_instr_num=0, semantics=ROBUST)
    alt = dataclasses.replace(cfg, interconnect=_IDEAL_VARIANT)
    traces = gen_uniform_random(cfg, 40, seed=2)
    ref = JaxEngine(cfg, traces).run()
    got = JaxEngine(alt, traces).run()
    assert _dumps_equal(ref.snapshots(), got.snapshots())
    assert _dumps_equal(ref.final_dumps(), got.final_dumps())
    assert ref.cycle == got.cycle
    assert ref.stats() == got.stats()
    assert got.link_stats() == {}
    spec = SpecEngine(alt, [list(t) for t in traces])
    spec.run()
    assert _dumps_equal(spec.final_dumps(), got.final_dumps())
    assert spec.link_tracker is None


def test_ideal_byte_identity_batched():
    cfg = SystemConfig(num_procs=4, max_instr_num=0, semantics=ROBUST)
    alt = dataclasses.replace(cfg, interconnect=_IDEAL_VARIANT)
    batch = [gen_uniform_random(cfg, 16, seed=s) for s in range(3)]
    ref = BatchJaxEngine(cfg, batch).run()
    got = BatchJaxEngine(alt, batch).run()
    for s in range(len(batch)):
        assert _dumps_equal(
            ref.system_final_dumps(s), got.system_final_dumps(s)
        )
    assert ref.stats() == got.stats()
    assert got.link_stats() == {}


def test_ideal_byte_identity_packed_pallas():
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    kw = dict(block=4, cycles_per_call=32, trace_window=8, gate=True)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    alt = dataclasses.replace(cfg, interconnect=_IDEAL_VARIANT)
    arrays = gen_uniform_random_arrays(cfg, 4, 8, seed=0)
    ref = PallasEngine(cfg, *arrays, packed=True, **kw).run()
    got = PallasEngine(alt, *arrays, packed=True, **kw).run()
    for f, v in ref.state.items():
        assert np.array_equal(np.asarray(v), np.asarray(got.state[f])), f
    assert ref.cycle == got.cycle
    assert ref.stats() == got.stats()


@pytest.mark.virtual_mesh
def test_ideal_byte_identity_data_sharded():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = SystemConfig(num_procs=4, max_instr_num=0, semantics=ROBUST)
    alt = dataclasses.replace(cfg, interconnect=_IDEAL_VARIANT)
    batch = [gen_uniform_random(cfg, 12, seed=s) for s in range(8)]
    ref = BatchJaxEngine(cfg, batch, data_shards=8).run()
    got = BatchJaxEngine(alt, batch, data_shards=8).run()
    for s in range(8):
        assert _dumps_equal(
            ref.system_final_dumps(s), got.system_final_dumps(s)
        )
    assert ref.stats() == got.stats()


def test_ideal_byte_identity_faulty():
    fault = FaultModel(drop=0.2, duplicate=0.05, reorder=0.05,
                       delay=0.1, seed=7)
    cfg = SystemConfig(
        num_procs=4, max_instr_num=0, semantics=ROBUST, fault=fault
    )
    alt = dataclasses.replace(
        cfg,
        fault=None,
        interconnect=dataclasses.replace(_IDEAL_VARIANT, fault=fault),
    )
    batch = [gen_uniform_random(cfg, 16, seed=s) for s in range(2)]
    ref = BatchJaxEngine(cfg, batch).run()
    got = BatchJaxEngine(alt, batch).run()
    for s in range(len(batch)):
        assert _dumps_equal(
            ref.system_final_dumps(s), got.system_final_dumps(s)
        )
    assert ref.stats() == got.stats()
    assert ref.stats().get("fault_retransmissions", 0) > 0


# -- (4) spec <-> JAX agreement under contention --------------------------

_TOPO_CASES = [
    ("mesh2d", {}),
    ("mesh2d", {"multicast": True, "combining": True}),
    ("mesh2d", {"link_bandwidth": 2, "combining": True}),
    ("torus2d", {}),
    ("torus2d", {"multicast": True}),
    ("torus2d", {"multicast": True, "combining": True}),
    ("hierarchical", {}),
    ("hierarchical", {"multicast": True, "combining": True}),
]


@pytest.mark.parametrize("topo,kw", _TOPO_CASES,
                         ids=[f"{t}-{'-'.join(k) or 'unicast'}"
                              for t, k in _TOPO_CASES])
def test_spec_jax_topology_agreement(topo, kw):
    cfg = _mesh_cfg(topo, **kw)
    traces = gen_uniform_random(cfg, 30, seed=1)
    spec = SpecEngine(cfg, [list(t) for t in traces])
    spec.run()
    jx = JaxEngine(cfg, traces).run()
    assert _dumps_equal(spec.snapshots(), jx.snapshots())
    assert _dumps_equal(spec.final_dumps(), jx.final_dumps())
    assert spec.cycle == jx.cycle
    _stats_agree(dict(spec.stats()), jx.stats())
    sl, jl = spec.link_stats(), jx.link_stats()
    assert sl["traversals"] == jl["traversals"]
    assert sl["max_load"] == jl["max_load"]


def test_topology_batch_lanes_match_singles():
    cfg = _mesh_cfg("mesh2d", procs=4, multicast=True)
    batch = [gen_uniform_random(cfg, 14, seed=s) for s in range(3)]
    be = BatchJaxEngine(cfg, batch).run()
    for s, traces in enumerate(batch):
        one = JaxEngine(cfg, traces).run()
        assert _dumps_equal(be.system_final_dumps(s), one.final_dumps())


def test_topology_delays_actually_bite():
    """The non-ideal run must cost cycles and say so in the counters —
    guards against the gate silently short-circuiting to ideal."""
    cfg = _mesh_cfg("hierarchical")
    traces = gen_uniform_random(cfg, 30, seed=1)
    ideal = JaxEngine(
        dataclasses.replace(cfg, interconnect=InterconnectConfig()), traces
    ).run()
    topo = JaxEngine(cfg, traces).run()
    assert topo.cycle > ideal.cycle
    assert topo.stats()["topo_delay_cycles"] > 0
    assert sum(topo.link_stats()["traversals"].values()) > 0


def test_analysis_topology_table_renders():
    from hpa2_tpu.analysis.topology import topology_table

    out = topology_table(nodes=4, rounds=2, topologies=["mesh2d"])
    assert "invalidation storm" in out
    assert "unicast" in out and "mcast+comb" in out
    # deterministic: the exact same table twice
    assert out == topology_table(nodes=4, rounds=2, topologies=["mesh2d"])


# -- (5) stats schema pin -------------------------------------------------


def test_stats_schema_only_when_nonzero():
    cfg = SystemConfig(num_procs=4, max_instr_num=0, semantics=ROBUST)
    traces = gen_uniform_random(cfg, 16, seed=0)
    clean = JaxEngine(cfg, traces).run().stats()
    assert not any(k.startswith(("fault_", "topo_")) for k in clean)

    topo = JaxEngine(_mesh_cfg(procs=4), traces).run().stats()
    assert topo["topo_delay_cycles"] > 0
    assert not any(k.startswith("fault_") for k in topo)


def test_fault_delay_counters_surface():
    fault = FaultModel(drop=0.2, duplicate=0.05, reorder=0.05,
                       delay=0.2, seed=5)
    cfg = SystemConfig(
        num_procs=4, max_instr_num=0, semantics=ROBUST,
        interconnect=InterconnectConfig(fault=fault),
    )
    eng = JaxEngine(cfg, gen_uniform_random(cfg, 24, seed=0)).run()
    stats = eng.stats()
    assert stats["fault_retransmissions"] > 0
    assert stats["fault_delays"] > 0
    # the same counters ride along in the stall post-mortem
    diag = stall_diagnostic(cfg, eng.state, "schema pin")
    assert diag.counters["fault_delays"] == stats["fault_delays"]
    assert (diag.counters["fault_retransmissions"]
            == stats["fault_retransmissions"])


# -- (6) checkpoints carry deliver_at -------------------------------------


def test_checkpoint_round_trip_with_topology(tmp_path):
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.ops.engine import (
        build_batched_run,
        build_batched_run_chunk,
    )
    from hpa2_tpu.ops.state import SimState, init_state_batched
    from hpa2_tpu.ops.step import quiescent
    from hpa2_tpu.utils.checkpoint import load_state, save_state

    cfg = _mesh_cfg(procs=4, multicast=True)
    arrays = gen_uniform_random_arrays(cfg, 2, 12, seed=0)
    straight = build_batched_run(cfg, max_cycles=100_000)(
        init_state_batched(cfg, *arrays)
    )
    chunk = build_batched_run_chunk(cfg, 5)
    st = chunk(init_state_batched(cfg, *arrays))
    path = str(tmp_path / "topo.npz")
    save_state(path, st, cfg)
    resumed, loaded_cfg = load_state(path)
    assert loaded_cfg == cfg  # incl. the nested InterconnectConfig
    while not bool(jnp.all(jax.vmap(quiescent)(resumed))):
        resumed = chunk(resumed)
    for name, a, b in zip(SimState._fields, straight, resumed):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_spec_checkpoint_round_trip_with_topology(tmp_path):
    from hpa2_tpu.utils.checkpoint import load_spec_state, save_spec_state

    cfg = _mesh_cfg(procs=4)
    traces = gen_uniform_random(cfg, 12, seed=4)
    straight = SpecEngine(cfg, [list(t) for t in traces])
    straight.run()

    eng = SpecEngine(cfg, [list(t) for t in traces])
    for _ in range(7):
        eng.step()
    path = str(tmp_path / "spec.json")
    save_spec_state(path, eng)
    resumed = load_spec_state(path)
    resumed.run()
    assert _dumps_equal(straight.final_dumps(), resumed.final_dumps())
    assert straight.cycle == resumed.cycle
    _stats_agree(dict(straight.stats()), dict(resumed.stats()))
    assert straight.link_stats() == resumed.link_stats()


def test_message_row_format_accepts_pre_topology_rows():
    from hpa2_tpu.models.protocol import Message, MsgType
    from hpa2_tpu.utils.checkpoint import _msg_from_list, _msg_to_list

    m = Message(MsgType.READ_REQUEST, sender=1, address=9, deliver_at=42)
    row = _msg_to_list(m)
    assert len(row) == 7 and row[-1] == 42
    assert _msg_from_list(row) == m
    legacy = _msg_from_list(row[:6])  # pre-topology 6-element row
    assert legacy.deliver_at == 0
    assert legacy.address == 9


# -- (7) backends without the model refuse it -----------------------------


def test_pallas_rejects_non_ideal():
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    cfg = _mesh_cfg(procs=4)
    with pytest.raises(ValueError, match="ideal topology only"):
        PallasEngine(cfg, *gen_uniform_random_arrays(cfg, 2, 8, seed=0))


def test_node_sharding_rejects_non_ideal():
    from hpa2_tpu.parallel.sharding import GridEngine, NodeShardedEngine

    cfg = _mesh_cfg(procs=4)
    traces = gen_uniform_random(cfg, 8, seed=0)
    with pytest.raises(ValueError, match="single-shard"):
        NodeShardedEngine(cfg, traces)
    with pytest.raises(ValueError, match="single-shard"):
        GridEngine(cfg, [traces])


def test_replay_rejects_non_ideal(reference_tests_dir):
    from hpa2_tpu.utils.trace import load_instruction_order, load_trace_dir

    cfg = SystemConfig(interconnect=InterconnectConfig(topology="mesh2d"))
    suite = str(reference_tests_dir / "test_1")
    traces = load_trace_dir(suite, cfg)
    order = load_instruction_order(
        os.path.join(suite, "instruction_order.txt")
    )
    with pytest.raises(ValueError, match="replay"):
        JaxEngine(cfg, traces, replay_order=order)


def test_cli_gates_non_ideal_backends(tmp_path, reference_tests_dir):
    from hpa2_tpu.cli import main

    suite = str(reference_tests_dir / "test_1")
    with pytest.raises(SystemExit, match="spec and"):
        main(["run", suite, "--backend", "pallas",
              "--topology", "mesh2d", "--out", str(tmp_path)])
    # the supported spelling runs end to end
    rc = main(["run", suite, "--backend", "jax", "--topology", "mesh2d",
               "--multicast", "--out", str(tmp_path)])
    assert rc == 0
    assert (tmp_path / "core_0_output.txt").exists()


# -- (8) interconnect-purity lint rule ------------------------------------


def test_lint_flags_rng_in_interconnect(tmp_path):
    from hpa2_tpu.analysis.lint import lint_file

    rel = os.path.join("hpa2_tpu", "interconnect", "bad.py")
    os.makedirs(os.path.dirname(str(tmp_path / rel)))
    (tmp_path / rel).write_text(
        "import random\n"
        "import numpy as np\n"
        "def jitter():\n"
        "    return random.random() + np.random.rand()\n"
    )
    findings = lint_file(str(tmp_path), rel)
    assert findings
    assert any("pure function of config + trace" in f.message
               for f in findings)

    (tmp_path / rel).write_text("import numpy as np\nX = np.zeros(3)\n")
    assert lint_file(str(tmp_path), rel) == []


def test_lint_repo_is_clean():
    from hpa2_tpu.analysis.lint import run_lint

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert run_lint(repo_root) == []
