"""Checkpoint / resume (SURVEY.md §5: the reference has none).

Gates: (1) save/load round-trips every SimState leaf bit-exactly;
(2) an interrupted run resumed from a checkpoint finishes in exactly
the state a straight run reaches; (3) the bench CLI's
--checkpoint-every path writes checkpoints and resumes from them.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.engine import (
    build_batched_run,
    build_batched_run_chunk,
)
from hpa2_tpu.ops.state import SimState, init_state_batched
from hpa2_tpu.ops.step import quiescent
from hpa2_tpu.utils.checkpoint import (
    latest_checkpoint,
    load_state,
    save_state,
)
from hpa2_tpu.utils.trace import gen_uniform_random_arrays

CFG = SystemConfig(num_procs=4, semantics=Semantics().robust())


def _state(batch=3, instrs=24, seed=0):
    return init_state_batched(
        CFG, *gen_uniform_random_arrays(CFG, batch, instrs, seed=seed)
    )


def _trees_equal(a: SimState, b: SimState):
    for name, la, lb in zip(SimState._fields, a, b):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name


def test_save_load_round_trip(tmp_path):
    st = _state()
    # advance a little so non-initial values are exercised
    st = build_batched_run_chunk(CFG, 7)(st)
    path = str(tmp_path / "ck.npz")
    save_state(path, st, CFG)
    loaded, config = load_state(path)
    assert config == CFG
    _trees_equal(st, loaded)


def test_resume_matches_straight_run(tmp_path):
    straight = build_batched_run(CFG, max_cycles=100_000)(_state())
    straight = jax.tree_util.tree_map(
        lambda x: x.block_until_ready(), straight
    )
    assert bool(jnp.all(jax.vmap(quiescent)(straight)))

    # interrupted: advance in chunks, checkpoint, reload mid-flight,
    # continue from the loaded state only
    chunk = build_batched_run_chunk(CFG, 5)
    st = chunk(_state())
    path = str(tmp_path / "mid.npz")
    save_state(path, st, CFG)
    resumed, _ = load_state(path)
    while not bool(jnp.all(jax.vmap(quiescent)(resumed))):
        resumed = chunk(resumed)
    _trees_equal(straight, resumed)


def test_load_rejects_non_checkpoint(tmp_path):
    p = tmp_path / "junk.npz"
    np.savez(str(p), meta_magic=np.array("nope"))
    with pytest.raises(ValueError, match="not a hpa2 checkpoint"):
        load_state(str(p))


def test_latest_checkpoint_picks_highest(tmp_path):
    st = _state(batch=1, instrs=4)
    for k in (1, 3, 2):
        save_state(str(tmp_path / f"ckpt_{k}.npz"), st, CFG)
    assert latest_checkpoint(str(tmp_path)).endswith("ckpt_3.npz")
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_cli_bench_checkpoint_completes_and_cleans(tmp_path):
    from hpa2_tpu.cli import main

    ckdir = str(tmp_path / "ck")
    args = [
        "bench", "--backend", "jax", "--nodes", "4", "--batch", "2",
        "--instrs", "16", "--robust", "--checkpoint-every", "10",
        "--checkpoint-dir", ckdir,
    ]
    assert main(args) == 0
    # completion clears the checkpoints (a rerun must not "resume" the
    # quiescent final state and report a zero-work benchmark)
    assert latest_checkpoint(ckdir) is None
    assert main(args) == 0


def test_cli_bench_resumes_from_mid_checkpoint(tmp_path, capsys):
    """Simulated crash: a mid-flight checkpoint in the dir is picked
    up (matching config+workload meta) and the run completes."""
    from hpa2_tpu.cli import main
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
    seed, batch, instrs = 0, 2, 16
    st = init_state_batched(
        cfg, *gen_uniform_random_arrays(cfg, batch, instrs, seed=seed)
    )
    st = build_batched_run_chunk(cfg, 10)(st)
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    save_state(
        str(ckdir / "ckpt_1.npz"), st, cfg,
        extra_meta={"batch": batch, "instrs": instrs,
                    "workload": "uniform", "seed": seed},
    )
    assert main([
        "bench", "--backend", "jax", "--nodes", "4", "--batch",
        str(batch), "--instrs", str(instrs), "--robust",
        "--checkpoint-every", "10", "--checkpoint-dir", str(ckdir),
    ]) == 0
    cap = capsys.readouterr()
    assert "resumed from" in cap.err
    # measured rate covers only post-resume work: the checkpointed
    # instructions are reported separately, not folded into ops/sec
    import json as _json

    rec = _json.loads(cap.out.strip().splitlines()[-1])
    assert rec["resumed_instrs"] == int(np.sum(np.asarray(st.n_instr)))
    assert rec["instrs"] == batch * 4 * instrs - rec["resumed_instrs"]


def test_cli_bench_rejects_mismatched_checkpoint(tmp_path):
    from hpa2_tpu.cli import main
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
    st = init_state_batched(
        cfg, *gen_uniform_random_arrays(cfg, 2, 16, seed=0)
    )
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    save_state(
        str(ckdir / "ckpt_1.npz"), st, cfg,
        extra_meta={"batch": 2, "instrs": 16, "workload": "uniform",
                    "seed": 0},
    )
    with pytest.raises(SystemExit, match="different config/workload"):
        main([
            "bench", "--backend", "jax", "--nodes", "4", "--batch", "2",
            "--instrs", "16", "--robust", "--seed", "5",
            "--checkpoint-every", "10", "--checkpoint-dir", str(ckdir),
        ])
