"""Deferred-send backpressure (capacity) differential tests.

The reference blocks a sender inside ``sendMessage`` when the
receiver's 256-deep ring is full (assignment.c:715-724, busy-wait).
The lockstep analog implemented by every engine: a node whose sends do
not fit keeps them in a per-node outbox, is blocked (neither handles
nor issues) until all of them drain, and delivery accepts candidates
in the global deterministic (phase, sender, slot) order up to each
receiver's free capacity (SURVEY.md §5 "masked/deferred-send
mechanism instead of blocking").

These tests run every engine at ``msg_buffer_size=4`` — small enough
that random and bursty traffic constantly saturates mailboxes — and
check bit-identical end state across engines plus bounded queues.
"""

import os

import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.utils.trace import gen_uniform_random

TINY = dict(num_procs=8, msg_buffer_size=4, semantics=Semantics().robust())


def tiny_config(**kw):
    return SystemConfig(**{**TINY, **kw})


def bursty_traces(n=8, per_core=30):
    """Everyone hammers node 0's home blocks: worst-case fan-in."""
    return [
        [Instr("W", (i % 4), i + j) for j in range(per_core)]
        for i in range(n)
    ]


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


# ---------------------------------------------------------------------------
# spec engine semantics
# ---------------------------------------------------------------------------

def test_spec_bounded_queues_bursty():
    cfg = tiny_config(max_instr_num=0)
    eng = SpecEngine(cfg, bursty_traces())
    eng.run(max_cycles=100_000)
    assert eng.instructions == 8 * 30
    assert eng.max_mailbox_depth <= cfg.msg_buffer_size


@pytest.mark.parametrize("seed", [0, 1])
def test_spec_bounded_queues_uniform(seed):
    cfg = tiny_config()
    eng = SpecEngine(cfg, gen_uniform_random(cfg, 32, seed=seed))
    eng.run(max_cycles=100_000)
    assert eng.max_mailbox_depth <= cfg.msg_buffer_size


# ---------------------------------------------------------------------------
# JAX engine differential
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_jax_matches_spec_tiny_cap(seed):
    from hpa2_tpu.ops.engine import JaxEngine

    cfg = tiny_config()
    traces = gen_uniform_random(cfg, 24, seed=seed)
    spec = SpecEngine(cfg, traces)
    spec.run(max_cycles=100_000)
    jx = JaxEngine(cfg, traces, max_cycles=100_000).run()
    assert _dicts(spec.final_dumps()) == _dicts(jx.final_dumps())
    assert _dicts(spec.snapshots()) == _dicts(jx.snapshots())
    assert spec.cycle == jx.cycle
    assert spec.messages == jx.messages


def test_jax_matches_spec_bursty():
    from hpa2_tpu.ops.engine import JaxEngine

    cfg = tiny_config(max_instr_num=0)
    traces = bursty_traces()
    spec = SpecEngine(cfg, traces)
    spec.run(max_cycles=100_000)
    jx = JaxEngine(cfg, traces, max_cycles=100_000).run()
    assert _dicts(spec.final_dumps()) == _dicts(jx.final_dumps())
    assert spec.cycle == jx.cycle


# ---------------------------------------------------------------------------
# sharded JAX engine differential (node axis over the CPU mesh)
# ---------------------------------------------------------------------------

def test_node_sharded_matches_spec_tiny_cap():
    import jax

    from hpa2_tpu.parallel.sharding import NodeShardedEngine, make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    cfg = tiny_config()
    traces = gen_uniform_random(cfg, 16, seed=3)
    spec = SpecEngine(cfg, traces)
    spec.run(max_cycles=100_000)
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=2), max_cycles=100_000
    ).run()
    assert _dicts(spec.final_dumps()) == _dicts(eng.final_dumps())
    assert spec.cycle == eng.cycle


# ---------------------------------------------------------------------------
# pallas engine differential (interpret mode on CPU)
# ---------------------------------------------------------------------------

def test_pallas_matches_spec_tiny_cap():
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import traces_to_arrays

    cfg = tiny_config()
    batch_traces = [gen_uniform_random(cfg, 16, seed=s) for s in (4, 5)]
    arrays = traces_to_arrays(cfg, batch_traces)
    pe = PallasEngine(
        cfg, *arrays, block=2, cycles_per_call=32, interpret=True
    ).run(max_cycles=100_000)
    for b, traces in enumerate(batch_traces):
        spec = SpecEngine(cfg, traces)
        spec.run(max_cycles=100_000)
        assert _dicts(spec.final_dumps()) == _dicts(
            pe.system_final_dumps(b)
        ), f"system {b}"
        assert _dicts(spec.snapshots()) == _dicts(
            pe.system_snapshots(b)
        ), f"system {b}"


# ---------------------------------------------------------------------------
# native lockstep differential + free-running completion
# ---------------------------------------------------------------------------

def _write_traces(traces, dirpath):
    os.makedirs(dirpath, exist_ok=True)
    for n, tr in enumerate(traces):
        with open(os.path.join(dirpath, f"core_{n}.txt"), "w") as f:
            for ins in tr:
                if ins.op == "R":
                    f.write(f"RD 0x{ins.address:02X}\n")
                else:
                    f.write(f"WR 0x{ins.address:02X} {ins.value}\n")


@pytest.mark.parametrize("seed", [0, 1])
def test_native_lockstep_matches_spec_tiny_cap(tmp_path, seed):
    from hpa2_tpu import native
    from hpa2_tpu.utils.dump import format_processor_state, parse_processor_dump

    native.ensure_built()
    cfg = tiny_config()
    traces = gen_uniform_random(cfg, 24, seed=seed)
    tdir = tmp_path / "traces"
    _write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    res = native.run_trace_dir(
        cfg, str(tdir), str(out), mode="lockstep", max_cycles=100_000
    )
    assert res.ok
    spec = SpecEngine(cfg, traces)
    spec.run(max_cycles=100_000)
    for i, dump in enumerate(spec.snapshots()):
        want = format_processor_state(dump, cfg)
        got = (out / f"core_{i}_output.txt").read_text()
        assert got == want, f"core_{i}"


def test_native_free_running_tiny_cap_never_hangs(tmp_path):
    """The free-running engine blocks on full rings like the reference
    (assignment.c:715-724).  With tiny rings, cyclically blocked
    senders CAN deadlock — the reference would spin forever; our
    contract is bounded time: either the run completes, or the
    watchdog aborts it with a diagnostic.  (Deterministic completion
    under tiny caps is the lockstep engines' guarantee, tested
    above.)"""
    from hpa2_tpu import native

    native.ensure_built()
    cfg = tiny_config(max_instr_num=0)  # uncapped trace load
    traces = gen_uniform_random(cfg, 32, seed=7)
    tdir = tmp_path / "traces"
    _write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    try:
        res = native.run_trace_dir(
            cfg, str(tdir), str(out), mode="omp", max_cycles=100_000
        )
        assert res.ok
        assert res.instructions == 8 * 32
    except native.NativeError as e:
        assert "watchdog" in str(e) or "livelock" in str(e)
