"""Record -> replay -> verify: the reference's core test workflow.

The reference's shipped multi-run fixtures were recorded by its
DEBUG_INSTR build (assignment.c:596-597 prints one line per issued
instruction; SURVEY.md §4): run free, capture the issue interleaving,
then validate any lockstep engine by replaying it.  Round 1 could only
*consume* recorded orders; these tests exercise the full production
loop — every engine records, every lockstep engine replays.

What each case may assert (SURVEY.md §7.4.2): a recorded issue order
pins the *issue* interleaving but underdetermines message-arrival
order, so free-running multi-threaded runs reproduce only up to the
legal dump-candidate envelope — exactly like the reference's own
fixtures (one of which is proven unreachable, see test_spec_parity).
Deterministic schedules (lockstep record, or free runs with no
cross-node traffic) must round-trip byte-exactly.
"""

import os

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.utils.dump import format_processor_state
from hpa2_tpu.utils.trace import (
    format_instruction_order,
    gen_local_only,
    gen_uniform_random,
    load_instruction_order,
    parse_instruction_order,
    validate_order_against_traces,
)

CFG = SystemConfig(num_procs=4, semantics=Semantics().robust())


def _write_traces(traces, dirpath):
    os.makedirs(dirpath, exist_ok=True)
    for n, tr in enumerate(traces):
        with open(os.path.join(dirpath, f"core_{n}.txt"), "w") as f:
            for ins in tr:
                if ins.op == "R":
                    f.write(f"RD 0x{ins.address:02X}\n")
                else:
                    f.write(f"WR 0x{ins.address:02X} {ins.value}\n")


def test_format_round_trips_reference_fixture(reference_tests_dir):
    """format_instruction_order is the exact inverse of the parser on
    a shipped fixture log (DEBUG_INSTR format, assignment.c:596-597)."""
    path = reference_tests_dir / "test_3" / "run_1" / "instruction_order.txt"
    text = path.read_text()
    assert format_instruction_order(parse_instruction_order(text)) == text


@pytest.mark.parametrize("seed", [0, 1])
def test_spec_record_then_batched_replay_is_exact(seed):
    """A lockstep free run's log, replayed in batched mode (records
    issued in the same cycle re-batch), reproduces the run exactly."""
    traces = gen_uniform_random(CFG, 20, seed=seed)
    free = SpecEngine(CFG, traces)
    free.run(100_000)
    assert len(free.issue_log) == sum(len(t) for t in traces)
    validate_order_against_traces(free.issue_log, traces)

    rep = SpecEngine(
        CFG, traces, replay_order=free.issue_log, replay_batched=True
    )
    rep.run(100_000)
    assert [d.__dict__ for d in free.final_dumps()] == [
        d.__dict__ for d in rep.final_dumps()
    ]
    assert [d.__dict__ for d in free.snapshots()] == [
        d.__dict__ for d in rep.snapshots()
    ]


def test_native_lockstep_record_matches_spec_log(tmp_path):
    """The native lockstep engine is bit-identical to the spec engine,
    so its recorded order file must equal the spec engine's log."""
    from hpa2_tpu import native

    native.ensure_built()
    traces = gen_uniform_random(CFG, 20, seed=2)
    tdir = tmp_path / "tr"
    _write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    orderp = tmp_path / "order.txt"
    res = native.run_trace_dir(
        CFG, str(tdir), str(out), mode="lockstep",
        record_order_path=str(orderp),
    )
    assert res.ok
    spec = SpecEngine(CFG, traces)
    spec.run(100_000)
    assert orderp.read_text() == format_instruction_order(spec.issue_log)


def test_native_free_run_local_traffic_round_trips_exact(tmp_path):
    """threads=4 free run with node-local-only traffic: every message
    stays on its own node, so the dumps are schedule-independent and
    the recorded order must replay to byte-identical dumps."""
    from hpa2_tpu import native

    native.ensure_built()
    traces = gen_local_only(CFG, 24, seed=3)
    tdir = tmp_path / "tr"
    _write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    orderp = tmp_path / "order.txt"
    res = native.run_trace_dir(
        CFG, str(tdir), str(out), mode="omp",
        record_order_path=str(orderp), threads=4,
    )
    assert res.ok
    order = load_instruction_order(str(orderp))
    validate_order_against_traces(order, traces)

    rep = SpecEngine(CFG, traces, replay_order=order, replay_batched=True)
    rep.run(100_000)
    for i, dump in enumerate(rep.snapshots()):
        got = (out / f"core_{i}_output.txt").read_text()
        assert got == format_processor_state(dump, CFG), f"core_{i}"


def _head_value_quirks_robust():
    import dataclasses

    return dataclasses.replace(
        Semantics().robust(),
        eager_write_request_memory=True,
        flush_invack_fills_old_value=True,
    )


@pytest.mark.parametrize(
    "sem_factory,count,seed",
    [
        # fixture semantics + NACK (the plain cross-traffic loop)
        (lambda: Semantics().robust(), 20, 4),
        # the HEAD-differential workflow under concurrency: both value
        # quirks active on the free-running native side AND the spec
        # replay side, so quirk semantics survive record -> replay ->
        # verify, not just lockstep runs
        (_head_value_quirks_robust, 16, 6),
    ],
    ids=["fixture-robust", "head-value-quirks"],
)
def test_native_free_run_cross_traffic_replay_validates(
    tmp_path, sem_factory, count, seed
):
    """threads=4 free run with cross-node traffic: the recorded order
    must be a valid interleaving, replay must complete with the full
    instruction count, and the free dumps sit inside (or near) the
    replay's candidate envelope.  Full candidate match for every node
    is NOT guaranteed (message order is underdetermined — the
    reference's own test_4/run_1 fixture is proven unreachable)."""
    from hpa2_tpu import native

    cfg = SystemConfig(num_procs=4, semantics=sem_factory())
    native.ensure_built()
    traces = gen_uniform_random(cfg, count, seed=seed)
    tdir = tmp_path / "tr"
    _write_traces(traces, str(tdir))

    # The soundness properties (valid interleaving, full replay) are
    # HARD on every attempt.  The envelope match is statistical — an
    # OS-scheduled free run occasionally lands outside every replay
    # dump candidate (message order is underdetermined; the
    # reference's own test_4/run_1 fixture is proven unreachable) —
    # so it gets a few fresh interleavings before failing.
    for attempt in range(3):
        out = tmp_path / f"out_{attempt}"
        out.mkdir()
        orderp = tmp_path / f"order_{attempt}.txt"
        res = native.run_trace_dir(
            cfg, str(tdir), str(out), mode="omp",
            record_order_path=str(orderp), threads=4,
        )
        assert res.ok
        order = load_instruction_order(str(orderp))
        assert len(order) == sum(len(t) for t in traces)
        validate_order_against_traces(order, traces)

        best_matches = 0
        for batched in (True, False):
            rep = SpecEngine(
                cfg, traces, replay_order=order, replay_batched=batched
            )
            rep.run(100_000)
            assert rep.instructions == len(order)
            matches = 0
            for i in range(cfg.num_procs):
                free_dump = (out / f"core_{i}_output.txt").read_text()
                cands = [
                    format_processor_state(d, cfg)
                    for d in rep.nodes[i].dump_candidates
                ]
                matches += free_dump in cands
            best_matches = max(best_matches, matches)
        if best_matches >= 1:
            break
    else:
        raise AssertionError(
            "no node of any free run matched a replay dump candidate "
            "across 3 interleavings — the recorded order no longer "
            "corresponds to the execution"
        )


def test_cli_record_and_replay_round_trip(tmp_path, reference_tests_dir):
    """CLI surface: run --record-order, then run --replay of that file
    reproduces identical dumps (spec backend; deterministic suite)."""
    from hpa2_tpu.cli import main

    suite = str(reference_tests_dir / "test_1")
    rec_out = tmp_path / "rec"
    rec_out.mkdir()
    orderp = tmp_path / "order.txt"
    assert main([
        "run", suite, "--backend", "spec", "--out", str(rec_out),
        "--record-order", str(orderp),
    ]) == 0
    assert orderp.exists() and orderp.read_text()

    rep_out = tmp_path / "rep"
    rep_out.mkdir()
    assert main([
        "run", suite, "--backend", "spec", "--out", str(rep_out),
        "--replay", str(orderp),
    ]) == 0
    for i in range(4):
        a = (rec_out / f"core_{i}_output.txt").read_text()
        b = (rep_out / f"core_{i}_output.txt").read_text()
        assert a == b, f"core_{i}"
