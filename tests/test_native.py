"""Native C++/OpenMP backend tests: build, fixture parity, differential
vs the Python spec oracle, and free-running termination.
"""

import glob
import os
import subprocess

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.utils.dump import format_processor_state
from hpa2_tpu.utils.parity import discover_run_sets
from hpa2_tpu.utils.trace import gen_uniform_random, load_trace_dir
from hpa2_tpu import native

CONFIG = SystemConfig()


@pytest.fixture(scope="session", autouse=True)
def built():
    native.ensure_built()


def write_traces(traces, dirpath):
    os.makedirs(dirpath, exist_ok=True)
    for n, tr in enumerate(traces):
        with open(os.path.join(dirpath, f"core_{n}.txt"), "w") as f:
            for ins in tr:
                if ins.op == "R":
                    f.write(f"RD 0x{ins.address:02X}\n")
                else:
                    f.write(f"WR 0x{ins.address:02X} {ins.value}\n")


@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_lockstep_deterministic_fixture_parity(
    reference_tests_dir, suite, tmp_path
):
    res = native.run_trace_dir(
        CONFIG, str(reference_tests_dir / suite), str(tmp_path)
    )
    assert res.ok
    for n in range(4):
        got = (tmp_path / f"core_{n}_output.txt").read_text()
        want = (reference_tests_dir / suite / f"core_{n}_output.txt").read_text()
        assert got == want, f"{suite} core_{n}"


@pytest.mark.parametrize("suite", ["test_3", "test_4"])
def test_lockstep_replay_candidate_parity(reference_tests_dir, suite, tmp_path):
    suite_dir = str(reference_tests_dir / suite)
    for run_dir in discover_run_sets(suite_dir):
        out = tmp_path / os.path.basename(run_dir)
        out.mkdir()
        res = native.run_trace_dir(
            CONFIG,
            suite_dir,
            str(out),
            replay_path=os.path.join(run_dir, "instruction_order.txt"),
            candidates=True,
        )
        assert res.ok
        for n in range(4):
            want = open(os.path.join(run_dir, f"core_{n}_output.txt")).read()
            cands = [
                open(p).read()
                for p in sorted(glob.glob(str(out / f"core_{n}_cand_*.txt")))
            ]
            if (
                os.path.relpath(run_dir, str(reference_tests_dir))
                == "test_4/run_1"
                and n == 2
            ):
                # documented fixture anomaly (test_fixture_anomaly.py)
                assert want not in cands
            else:
                assert want in cands, f"{run_dir} core_{n}"


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_lockstep_differential_random(tmp_path, seed):
    cfg = SystemConfig(
        num_procs=8, max_instr_num=0, semantics=Semantics().robust()
    )
    traces = gen_uniform_random(cfg, 80, seed=seed)
    tdir = tmp_path / "traces"
    write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    res = native.run_trace_dir(cfg, str(tdir), str(out), final_dump=True)
    assert res.ok
    spec = SpecEngine(cfg, traces)
    spec.run()
    assert res.cycles == spec.cycle
    assert res.instructions == spec.counters["instructions"]
    for n, dump in enumerate(spec.final_dumps()):
        got = (out / f"core_{n}_output.txt").read_text()
        assert got == format_processor_state(dump, cfg), f"core_{n}"


def test_omp_deterministic_suites_match_fixtures(
    reference_tests_dir, tmp_path
):
    """Node-local-only suites are scheduling-independent: the
    free-running OpenMP engine must reproduce fixtures exactly."""
    for suite in ["test_1", "test_2"]:
        out = tmp_path / suite
        out.mkdir()
        res = native.run_trace_dir(
            CONFIG, str(reference_tests_dir / suite), str(out), mode="omp"
        )
        assert res.ok
        for n in range(4):
            got = (out / f"core_{n}_output.txt").read_text()
            want = (
                reference_tests_dir / suite / f"core_{n}_output.txt"
            ).read_text()
            assert got == want


def test_omp_terminates_on_cross_node_traffic(tmp_path):
    """The reference never terminates and livelocks on test_4-style
    traces (SURVEY.md §6.3); the rebuilt free-running engine reaches
    quiescence with the robust policy."""
    cfg = SystemConfig(
        num_procs=4, max_instr_num=0, semantics=Semantics().robust()
    )
    traces = gen_uniform_random(cfg, 200, seed=7)
    tdir = tmp_path / "traces"
    write_traces(traces, str(tdir))
    out = tmp_path / "out"
    out.mkdir()
    res = native.run_trace_dir(cfg, str(tdir), str(out), mode="omp")
    assert res.ok and res.instructions == 800


def test_native_bench_counters():
    cfg = SystemConfig(max_instr_num=0, semantics=Semantics().robust())
    res = native.bench_random(cfg, 500, seed=1, mode="lockstep")
    assert res.ok and res.instructions == 2000
    assert res.seconds > 0


def test_native_rejects_too_many_nodes():
    cfg = SystemConfig(num_procs=65, mem_size=16)
    with pytest.raises(native.NativeError):
        native.bench_random(cfg, 10)


def test_cli_runs_like_reference(reference_tests_dir, tmp_path):
    """CLI shape: hpa2sim TRACE_DIR writes core_<n>_output.txt to CWD
    (README.md:99-106 usage, minus the never-terminating loop)."""
    bin_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native", "build", "hpa2sim",
    )
    proc = subprocess.run(
        [bin_path, str(reference_tests_dir / "sample")],
        cwd=str(tmp_path),
        capture_output=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    want = (reference_tests_dir / "sample" / "core_0_output.txt").read_text()
    assert (tmp_path / "core_0_output.txt").read_text() == want
