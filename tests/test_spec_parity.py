"""Golden fixture parity for the Python spec engine (SURVEY.md §7.2 gate 2).

* sample / test_1 / test_2 — deterministic suites: the engine's
  canonical (earliest) dump-at-local-completion snapshot must equal the
  fixture byte for byte.
* test_3 (2 run sets) / test_4 (4 run sets) — nondeterministic suites:
  replayed from each run set's recorded ``instruction_order.txt``.  The
  reference's dump moment is OS-scheduling dependent (a thread can be
  descheduled between finishing its trace and dumping), so a node
  matches if ANY of its legal dump-timing candidates reproduces the
  fixture byte-exactly.

KNOWN ANOMALY — test_4/run_1/core_2: the fixture shows block 0x20 as
``dir U`` with memory 40 and a cache line INVALID/40.  Exhaustive
reachability analysis over the reference protocol (all message-arrival
interleavings, all issue interleavings consistent with per-node program
order, all dump points — see test_fixture_anomaly.py) proves the only
reachable INVALID/40 dump states have ``dir EM{3}`` or ``S{1,3}``:
the fixture's directory row is unreachable and therefore cannot have
been produced by the same execution as the paired instruction_order.txt
(nor by any execution of the shipped protocol).  The parity gate pins
this node to "matches a candidate except exactly that one directory
row" so any further drift still fails loudly.
"""

import os

import pytest

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import DirState
from hpa2_tpu.utils.dump import format_processor_state, parse_processor_dump
from hpa2_tpu.utils.parity import (
    check_suite,
    diff_against_fixtures,
    discover_run_sets,
    replay_run_set,
)

CONFIG = SystemConfig()

DETERMINISTIC_SUITES = ["sample", "test_1", "test_2"]
REPLAY_SUITES = ["test_3", "test_4"]

ANOMALY_RUN = "test_4/run_1"
ANOMALY_NODE = 2
ANOMALY_BLOCK = 0  # block index of address 0x20 at its home node 2


@pytest.mark.parametrize("suite", DETERMINISTIC_SUITES)
def test_deterministic_suite_byte_exact(reference_tests_dir, suite):
    suite_dir = str(reference_tests_dir / suite)
    # strict: canonical earliest snapshot only, no candidate slack
    results = check_suite(suite_dir, CONFIG, allow_candidates=False)
    for run_dir, diffs in results.items():
        assert not diffs, f"{run_dir}:\n" + "\n".join(diffs.values())


@pytest.mark.parametrize("suite", REPLAY_SUITES)
def test_replay_suite_candidate_exact(reference_tests_dir, suite):
    suite_dir = str(reference_tests_dir / suite)
    for run_dir in discover_run_sets(suite_dir):
        engine = replay_run_set(suite_dir, run_dir, CONFIG)
        diffs = diff_against_fixtures(engine, run_dir, CONFIG)
        rel = os.path.relpath(run_dir, str(reference_tests_dir))
        if rel == ANOMALY_RUN:
            assert set(diffs) <= {ANOMALY_NODE}, (
                f"{rel}: unexpected mismatches beyond the documented "
                f"anomaly:\n" + "\n".join(diffs.values())
            )
            _check_anomaly_envelope(engine, run_dir)
        else:
            assert not diffs, f"{rel}:\n" + "\n".join(diffs.values())


def _check_anomaly_envelope(engine, run_dir):
    """The anomalous fixture must differ from some legal candidate in
    exactly the one proven-unreachable directory row (block 0x20:
    fixture U/{} vs engine EM/{3})."""
    node = engine.nodes[ANOMALY_NODE]
    with open(os.path.join(run_dir, f"core_{ANOMALY_NODE}_output.txt")) as f:
        fixture = parse_processor_dump(f.read())
    for cand in node.dump_candidates:
        same = (
            cand.memory == fixture.memory
            and cand.cache_addr == fixture.cache_addr
            and cand.cache_value == fixture.cache_value
            and cand.cache_state == fixture.cache_state
        )
        dirs_same_elsewhere = all(
            (cand.dir_state[i], cand.dir_sharers[i])
            == (fixture.dir_state[i], fixture.dir_sharers[i])
            for i in range(CONFIG.mem_size)
            if i != ANOMALY_BLOCK
        )
        if same and dirs_same_elsewhere:
            assert fixture.dir_state[ANOMALY_BLOCK] == DirState.U
            assert fixture.dir_sharers[ANOMALY_BLOCK] == 0
            assert cand.dir_state[ANOMALY_BLOCK] == DirState.EM
            assert cand.dir_sharers[ANOMALY_BLOCK] == 0b1000  # owner {3}
            return
    pytest.fail(
        "no candidate matches the anomalous fixture modulo the documented "
        "directory row — engine behavior drifted"
    )


def test_engine_reports_counters(reference_tests_dir):
    suite_dir = str(reference_tests_dir / "test_1")
    engine = replay_run_set(suite_dir, suite_dir, CONFIG)
    c = engine.counters
    assert c["instructions"] == 68  # 17 instrs x 4 cores
    assert c["msgs_total"] > 0
    assert engine.max_mailbox_depth <= CONFIG.msg_buffer_size


def test_free_run_matches_fixtures_on_deterministic_suites(reference_tests_dir):
    """Without a replay order (free-running lockstep), node-local-only
    suites must still reproduce fixtures: scheduling cannot matter."""
    from hpa2_tpu.models.spec_engine import SpecEngine
    from hpa2_tpu.utils.trace import load_trace_dir

    for suite in ["test_1", "test_2"]:
        suite_dir = str(reference_tests_dir / suite)
        traces = load_trace_dir(suite_dir, CONFIG)
        engine = SpecEngine(CONFIG, traces)
        engine.run()
        for node in engine.nodes:
            with open(os.path.join(suite_dir, f"core_{node.id}_output.txt")) as f:
                expected = f.read()
            assert format_processor_state(node.snapshot, CONFIG) == expected
