"""Seeded randomized differential sweep across all four engines.

200+ random uniform-traffic systems over varied geometries — tiny
mailbox capacities (backpressure), odd cache/memory sizes, multi-word
sharer masks, the pallas packing limits — every engine that supports
the geometry must produce identical final state and counters.  This
pins the protocol while the kernels are being tuned for performance
(round-3 verdict item 8); geometry/engine coverage:

    spec      all
    xla       all (the comparison pivot)
    native    num_procs <= 64; dumps byte-compared via the reference
              (or wide) text format
    pallas    interpret mode; packed-word path below 22 nodes,
              split-plane sharer words beyond (the 33-node row)

Runs under the ``sweep`` marker as part of the default suite.
"""

import os

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.utils.dump import format_processor_state
from hpa2_tpu.utils.trace import gen_uniform_random_arrays

ROBUST = Semantics().robust()

# (config, batch, instrs_per_core, engines beyond spec+xla)
GEOMETRIES = [
    (SystemConfig(num_procs=4, cache_size=4, mem_size=16,
                  msg_buffer_size=256, semantics=ROBUST),
     30, 16, ("native", "pallas")),
    # tiny capacity: heavy backpressure, and some seeds hit the
    # bounded-capacity deadlock (cyclically blocked senders) — the
    # engines must AGREE on which systems deadlock (the reference
    # would spin forever in sendMessage there, assignment.c:715-724)
    (SystemConfig(num_procs=8, cache_size=2, mem_size=8,
                  msg_buffer_size=4, semantics=ROBUST),
     38, 16, ("native",)),
    (SystemConfig(num_procs=8, cache_size=4, mem_size=16,
                  msg_buffer_size=16, semantics=ROBUST),
     30, 16, ("native", "pallas")),  # the bench geometry
    (SystemConfig(num_procs=3, cache_size=3, mem_size=5,
                  msg_buffer_size=8, semantics=ROBUST),
     40, 20, ("native", "pallas")),  # odd, non-power-of-two sizes
    (SystemConfig(num_procs=12, cache_size=4, mem_size=16,
                  msg_buffer_size=32, semantics=ROBUST),
     26, 12, ("native",)),
    (SystemConfig(num_procs=21, cache_size=2, mem_size=8,
                  msg_buffer_size=16, semantics=ROBUST),
     12, 10, ("native", "pallas")),  # pallas packed-word limit
    (SystemConfig(num_procs=40, cache_size=4, mem_size=8,
                  msg_buffer_size=32, semantics=ROBUST),
     12, 10, ("native",)),       # multi-word sharer mask (2 words)
    (SystemConfig(num_procs=33, cache_size=4, mem_size=8,
                  msg_buffer_size=32, semantics=ROBUST),
     12, 10, ("pallas",)),       # 2-word mask; pallas split-plane mode
]

assert sum(g[1] for g in GEOMETRIES) >= 200


def _traces(op, addr, val, b, n):
    return [
        [
            Instr("W", int(a), int(v)) if o == 1 else Instr("R", int(a))
            for o, a, v in zip(op[b, m], addr[b, m], val[b, m])
        ]
        for m in range(n)
    ]


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


def _sweep(cfg, batch, extra, arrays, tmp_path, allow_stall):
    """Differential body shared by the uniform and adversarial sweeps:
    every engine that supports the geometry must produce identical
    final state/counters, and all must AGREE on which systems stall."""
    n = cfg.num_procs
    op, addr, val, length = arrays

    # --- pallas (interpret): full batch in one engine.  Its stall
    # signal is batch-wide (one status scalar), so on StallError the
    # per-system dump compare is skipped and stall agreement is
    # asserted at batch granularity after the loop.
    pe = None
    pallas_stalled = False
    if "pallas" in extra:
        from hpa2_tpu.ops.pallas_engine import PallasEngine

        pe = PallasEngine(cfg, op, addr, val, length,
                          block=batch, cycles_per_call=64,
                          interpret=True)
        try:
            pe.run(max_cycles=200_000)
        except StallError:
            if not allow_stall:
                raise
            pallas_stalled = True
            pe = None

    from hpa2_tpu.models.spec_engine import SpecEngine
    from hpa2_tpu.ops.engine import JaxEngine

    native_mod = None
    if "native" in extra:
        from hpa2_tpu import native as native_mod

    stalled = 0
    for b in range(batch):
        traces = _traces(op, addr, val, b, n)

        spec = SpecEngine(cfg, traces)
        try:
            spec.run(max_cycles=50_000)
            spec_stalled = False
        except StallError:
            spec_stalled = True
            stalled += 1

        # xla per system (compile shared across b: identical shapes)
        jx = JaxEngine(cfg, traces, max_cycles=50_000)
        if spec_stalled:
            with pytest.raises(StallError):
                jx.run()
        else:
            jx.run()
            want = _dicts(jx.final_dumps())
            assert _dicts(spec.final_dumps()) == want, (
                f"spec diverged b={b}"
            )
            assert spec.instructions == jx.instructions
            assert spec.messages == jx.messages

        if pe is not None and not spec_stalled:
            # (a stalled system would have raised in pe.run() above;
            # guard anyway so `want` is never read undefined)
            assert _dicts(pe.system_final_dumps(b)) == want, (
                f"pallas diverged b={b}"
            )

        if native_mod is not None:
            from tests.test_native import write_traces

            tr_dir = tmp_path / f"tr_{b}"
            out = tmp_path / f"out_{b}"
            write_traces(traces, str(tr_dir))
            os.makedirs(out, exist_ok=True)
            if spec_stalled:
                with pytest.raises(native_mod.NativeError,
                                   match="livelock"):
                    native_mod.run_trace_dir(
                        cfg, str(tr_dir), str(out), mode="lockstep",
                        final_dump=True, max_cycles=50_000,
                    )
                continue
            res = native_mod.run_trace_dir(
                cfg, str(tr_dir), str(out), mode="lockstep",
                final_dump=True, max_cycles=50_000,
            )
            assert int(res.instructions) == spec.instructions, (
                f"native instrs diverged b={b}"
            )
            assert int(res.messages) == spec.messages, (
                f"native msgs diverged b={b}"
            )
            for node, nd in enumerate(jx.final_dumps()):
                got = (out / f"core_{node}_output.txt").read_text()
                assert got == format_processor_state(nd, cfg), (
                    f"native dump diverged b={b} node={node}"
                )
    # deadlock is possible only where the caller expects it (the
    # tiny-capacity geometries)
    assert stalled == 0 or allow_stall
    if pallas_stalled:
        assert stalled > 0, (
            "pallas reported a batch stall but no spec system stalled"
        )
    elif pe is not None:
        # stall agreement is two-way: a quiesced pallas batch means
        # NO spec system may have stalled (the batch status scalar
        # ORs every system's liveness)
        assert stalled == 0, (
            f"{stalled} spec systems stalled but the pallas batch "
            "quiesced"
        )


@pytest.mark.sweep
@pytest.mark.parametrize("gi", range(len(GEOMETRIES)))
def test_random_differential_geometry(gi, tmp_path):
    cfg, batch, t, extra = GEOMETRIES[gi]
    arrays = gen_uniform_random_arrays(cfg, batch, t, seed=1000 + gi)
    _sweep(cfg, batch, extra, arrays, tmp_path,
           allow_stall=cfg.msg_buffer_size <= 4)


# Adversarial liveness sweep (VERDICT round-4 item 8): traces biased
# toward the reference's hang class — eviction ping-pong on shared
# homes with index-0 cache collisions (SURVEY.md §6.3) — across tiny
# mailbox capacities.  The robust (NACK) protocol must stay live, and
# all engines must agree system-by-system.
ADVERSARIAL_GEOMETRIES = [
    (SystemConfig(num_procs=4, cache_size=4, mem_size=16,
                  msg_buffer_size=8, semantics=ROBUST),
     20, 24, ("native", "pallas")),
    # tiny capacity: backpressure deadlock is reachable; engines must
    # agree on which seeds hit it
    (SystemConfig(num_procs=8, cache_size=2, mem_size=8,
                  msg_buffer_size=4, semantics=ROBUST),
     20, 16, ("native",)),
    (SystemConfig(num_procs=8, cache_size=4, mem_size=8,
                  msg_buffer_size=6, semantics=ROBUST),
     16, 20, ("native", "pallas")),
    (SystemConfig(num_procs=12, cache_size=4, mem_size=16,
                  msg_buffer_size=8, semantics=ROBUST),
     10, 14, ("native",)),
]


@pytest.mark.sweep
@pytest.mark.parametrize("gi", range(len(ADVERSARIAL_GEOMETRIES)))
def test_adversarial_liveness_geometry(gi, tmp_path):
    from hpa2_tpu.utils.trace import gen_eviction_pingpong_arrays

    cfg, batch, t, extra = ADVERSARIAL_GEOMETRIES[gi]
    arrays = gen_eviction_pingpong_arrays(cfg, batch, t, seed=7000 + gi)
    _sweep(cfg, batch, extra, arrays, tmp_path,
           allow_stall=cfg.msg_buffer_size <= 6)


# Slow tier (scripts/run_slow.sh): the same differential body at the
# scale the tier-1 sweeps can't afford — longer traces (deeper
# protocol histories: more evictions per line, more NACK re-serves per
# address), larger batches, and a wider node-count spread including
# both split-plane widths.  Since the streaming HBM path became the
# PallasEngine default these also soak the windowless streaming
# program at batch sizes where a window boundary bug would compound.
SLOW_GEOMETRIES = [
    (SystemConfig(num_procs=8, cache_size=4, mem_size=16,
                  msg_buffer_size=16, semantics=ROBUST),
     32, 48, ("native", "pallas")),  # bench geometry, 3x trace depth
    (SystemConfig(num_procs=16, cache_size=4, mem_size=16,
                  msg_buffer_size=32, semantics=ROBUST),
     24, 24, ("native", "pallas")),  # widest packed-word node count
    (SystemConfig(num_procs=33, cache_size=4, mem_size=8,
                  msg_buffer_size=32, semantics=ROBUST),
     10, 16, ("native", "pallas")),  # split-plane SW=2, deeper
    (SystemConfig(num_procs=48, cache_size=2, mem_size=8,
                  msg_buffer_size=16, semantics=ROBUST),
     6, 10, ("native", "pallas")),   # SW=2 high word occupancy
]


@pytest.mark.slow
@pytest.mark.sweep
@pytest.mark.parametrize("gi", range(len(SLOW_GEOMETRIES)))
def test_slow_random_differential_geometry(gi, tmp_path):
    cfg, batch, t, extra = SLOW_GEOMETRIES[gi]
    arrays = gen_uniform_random_arrays(cfg, batch, t, seed=5000 + gi)
    _sweep(cfg, batch, extra, arrays, tmp_path, allow_stall=False)
