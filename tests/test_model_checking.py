"""Bounded liveness model check of the rebuilt protocol — driving the
REAL SpecEngine handlers (not a re-model) under EVERY free-running
interleaving of message handling and instruction issue.

The reference's free-running execution can interleave a node's
instruction issue with any message arrival order; its drop-policy
intervention handling livelocks on some of those interleavings
(SURVEY.md §6.3: a WRITEBACK_* reaching an owner that already evicted
is silently dropped, leaving the requester waiting forever).  The
lockstep engines sidestep the interleavings but the PROTOCOL claim at
scale is stronger: with the NACK policy, every reachable state can
still reach quiescence.  This checker proves that claim exhaustively
for bounded configurations by:

  * exploring the full state graph (BFS, memoized on frozen engine
    state) where an enabled action is either "node i handles its
    mailbox head" or "node i issues its next instruction" (enabled
    when its mailbox is empty and it is not waiting — the reference's
    drain-all-then-issue loop shape, assignment.c:153-699);
  * instant per-receiver-FIFO delivery in emission order (capacity
    backpressure is a separate mechanism, pinned by
    tests/test_backpressure.py; an unbounded mailbox isolates
    protocol livelock from capacity deadlock);
  * asserting, under Semantics().robust():  (a) every terminal state
    (no enabled action) is quiescent — deadlock freedom; (b) from
    EVERY reachable state a quiescent state remains reachable —
    livelock freedom (EF quiescent everywhere);
  * asserting, under the parity default drop policy, that DOOMED
    states exist for the stale-eviction workload and every one of
    them shows the documented signature (some node waiting forever) —
    the reference's unsoundness, reproduced exhaustively rather than
    by sampled fuzzing.

The exploration is exact, not sampled: a state-count cap guards
against blowup, and the test FAILS if the cap is hit (a truncated
exploration proves nothing).
"""

import copy

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import Instr, Message, MsgType
from hpa2_tpu.models.spec_engine import SpecEngine

STATE_CAP = 400_000


class _Model(SpecEngine):
    """SpecEngine with instant delivery: sends append straight to the
    receiver's FIFO in emission order (free-running semantics)."""

    def _send(self, phase, receiver, msg):  # noqa: ARG002
        self.nodes[receiver].mailbox.append(msg)


def _freeze(eng):
    return tuple(
        (
            tuple((l.address, l.value, int(l.state)) for l in n.cache),
            tuple(n.memory),
            # owner rides along for the owner-plane protocols (MOESI's
            # SO owner, MESIF's forwarder); constant NO_PROC under
            # MESI, so the MESI graphs are unchanged
            tuple((int(d.state), d.sharers, d.owner)
                  for d in n.directory),
            n.waiting,
            n.pending_write,
            n.pc,
            tuple(
                (int(m.type), m.sender, m.address, m.value, m.sharers,
                 m.second_receiver)
                for m in n.mailbox
            ),
        )
        for n in eng.nodes
    )


def _thaw(config, traces, frozen):
    eng = _Model(config, traces)
    for n, fr in zip(eng.nodes, frozen):
        lines, mem, directory, waiting, pw, pc, box = fr
        for line, (a, v, s) in zip(n.cache, lines):
            line.address, line.value, line.state = a, v, s
        n.memory = list(mem)
        for d, (ds, sh, ow) in zip(n.directory, directory):
            d.state, d.sharers, d.owner = ds, sh, ow
        n.waiting = waiting
        n.pending_write = pw
        n.pc = pc
        n.mailbox.clear()
        for t, snd, addr, val, sh, second in box:
            n.mailbox.append(
                Message(MsgType(t), snd, addr, val, sh, second)
            )
    return eng


def _enabled(eng):
    acts = []
    for n in eng.nodes:
        if n.mailbox:
            acts.append(("handle", n.id))
        elif not n.waiting and n.pc < len(n.trace):
            acts.append(("issue", n.id))
    return acts


def _apply(eng, act):
    kind, i = act
    node = eng.nodes[i]
    if kind == "handle":
        eng._handle(node, node.mailbox.popleft())
    else:
        eng._issue(node)


def _is_quiescent(frozen, traces):
    return all(
        fr[5] >= len(traces[i]) and not fr[3] and not fr[6]
        for i, fr in enumerate(frozen)
    )


def _explore(config, traces):
    """Full reachable state graph.  Returns (states, edges, quiescent,
    terminal_nonquiescent)."""
    init = _freeze(_Model(config, traces))
    index = {init: 0}
    states = [init]
    edges = []            # (src, dst)
    quiescent = set()
    stuck = set()
    frontier = [0]
    while frontier:
        nxt = []
        for si in frontier:
            fr = states[si]
            eng = _thaw(config, traces, fr)
            acts = _enabled(eng)
            if not acts:
                if _is_quiescent(fr, traces):
                    quiescent.add(si)
                else:
                    stuck.add(si)
                continue
            for act in acts:
                # the last action can mutate the thawed engine in place
                child = eng if act is acts[-1] else copy.deepcopy(eng)
                _apply(child, act)
                cf = _freeze(child)
                ci = index.get(cf)
                if ci is None:
                    ci = len(states)
                    index[cf] = ci
                    states.append(cf)
                    nxt.append(ci)
                    assert len(states) <= STATE_CAP, (
                        "state cap hit — exploration would be "
                        "truncated, result meaningless"
                    )
                edges.append((si, ci))
        frontier = nxt
    return states, edges, quiescent, stuck


def _can_reach(n_states, edges, targets):
    """Reverse reachability: which states can reach ``targets``."""
    rev = [[] for _ in range(n_states)]
    for s, d in edges:
        rev[d].append(s)
    seen = set(targets)
    stack = list(targets)
    while stack:
        x = stack.pop()
        for p in rev[x]:
            if p not in seen:
                seen.add(p)
                stack.append(p)
    return seen


def _mk(policy, traces_for, protocol="mesi"):
    sem = Semantics().robust() if policy == "nack" else Semantics()
    config = SystemConfig(
        num_procs=3, cache_size=1, mem_size=2, msg_buffer_size=64,
        max_instr_num=0, semantics=sem, protocol=protocol,
    )
    return config, traces_for(config)


def _stale_eviction_traces(config):
    """SURVEY.md §6.3's hang class: P1 gains ownership of address 0
    then evicts it (cache_size=1 collision with address 2, a different
    home) while P2's read races the eviction."""
    del config
    return [
        [],
        [Instr("W", 0, 7), Instr("R", 2)],
        [Instr("R", 0)],
    ]


def _sharing_traces(config):
    """Read sharing + upgrade + last-sharer notify traffic on one hot
    block, home node 0 itself a sharer."""
    del config
    return [
        [Instr("R", 0)],
        [Instr("R", 0), Instr("W", 0, 9)],
        [Instr("R", 0), Instr("R", 2)],
    ]


def _heavier_traces(config):
    """Writes, upgrades, evictions and re-reads interleaved on two
    colliding addresses — ~36K reachable states, the largest bounded
    configuration the suite proves exhaustively (~11s)."""
    del config
    return [
        [Instr("R", 0), Instr("W", 0, 5)],
        [Instr("R", 0), Instr("W", 0, 9), Instr("R", 2)],
        [Instr("R", 0), Instr("R", 2), Instr("R", 0)],
    ]


@pytest.mark.parametrize(
    "traces_for",
    [_stale_eviction_traces, _sharing_traces, _heavier_traces],
)
def test_robust_protocol_livelock_free(traces_for):
    config, traces = _mk("nack", traces_for)
    states, edges, quiescent, stuck = _explore(config, traces)
    assert not stuck, (
        f"deadlock: {len(stuck)} terminal non-quiescent states"
    )
    assert quiescent, "no quiescent state reachable at all"
    ok = _can_reach(len(states), edges, quiescent)
    doomed = set(range(len(states))) - ok
    assert not doomed, (
        f"livelock: {len(doomed)}/{len(states)} reachable states "
        "cannot reach quiescence under the NACK policy"
    )


@pytest.mark.parametrize("protocol", ["moesi", "mesif"])
@pytest.mark.parametrize(
    "traces_for", [_stale_eviction_traces, _sharing_traces]
)
def test_table_variant_protocols_livelock_free(traces_for, protocol):
    """The PR-13 compiled-table variants carry the same liveness claim
    as the frozen MESI reference: under the NACK policy every
    reachable MOESI/MESIF state (owner plane included in the frozen
    state — cache-to-cache forwards and SO ownership change the graph)
    is deadlock-free and can still reach quiescence.  The exploration
    stays exact: the state cap aborts the test rather than truncating
    (the assert lives in _explore)."""
    config, traces = _mk("nack", traces_for, protocol)
    states, edges, quiescent, stuck = _explore(config, traces)
    assert not stuck, (
        f"{protocol}: deadlock — {len(stuck)} terminal non-quiescent "
        "states"
    )
    assert quiescent, f"{protocol}: no quiescent state reachable"
    ok = _can_reach(len(states), edges, quiescent)
    doomed = set(range(len(states))) - ok
    assert not doomed, (
        f"{protocol}: livelock — {len(doomed)}/{len(states)} reachable "
        "states cannot reach quiescence under the NACK policy"
    )
    # the variant actually exercises its owner plane: some reachable
    # state tracks an owner/forwarder (otherwise this parametrization
    # proves nothing beyond MESI)
    assert any(
        any(any(ow >= 0 for _, _, ow in f[2]) for f in states[si])
        for si in range(len(states))
    ), f"{protocol}: no reachable state ever tracked an owner"


def test_freerunning_interleavings_break_strict_coherence():
    """A finding the checker PROVED, kept as a pinned negative result:
    the reference protocol's optimistic directory transitions
    (assignment.c:230-231 — dir goes S and the requester is recorded
    BEFORE the owner's flush arrives) admit free-running interleavings
    whose final quiescent state violates strict coherence, e.g. a
    reader served stale memory during the intervention window keeps a
    SHARED copy of the old value next to the flushed new one (SURVEY.md
    §6.3 root defect (c); NACK heals the LIVENESS hole, not this).
    The exhaustive exploration of the sharing workload must contain at
    least one such quiescent state — while the deterministic lockstep
    schedule the production engines run keeps the full invariant set
    (pinned on sampled workloads by test_observability).  If this test
    ever fails, the protocol semantics drifted from the reference's
    optimistic design — update SURVEY.md §6.3."""
    from hpa2_tpu.utils.invariants import check_invariants

    config, traces = _mk("nack", _sharing_traces)
    states, edges, quiescent, stuck = _explore(config, traces)
    assert not stuck
    violating = 0
    for si in quiescent:
        eng = _thaw(config, traces, states[si])
        if check_invariants([n.dump() for n in eng.nodes], config):
            violating += 1
    assert violating > 0, (
        "expected the optimistic-transition race to be reachable"
    )
    assert violating < len(quiescent), (
        "some interleavings (e.g. the lockstep-like ones) must still "
        "end coherent"
    )


@pytest.mark.parametrize(
    "traces_for", [_stale_eviction_traces, _sharing_traces]
)
def test_drop_policy_has_doomed_states(traces_for):
    """The parity-default drop policy (the reference's semantics) IS
    unsound under free-running interleavings: the checker must find
    doomed states (15 on the stale-eviction workload, 169 on the
    sharing workload — including true terminal deadlocks), and each
    shows the documented signature — a node waiting for a reply that
    can no longer arrive (SURVEY.md §6.3 root defect (b))."""
    config, traces = _mk("drop", traces_for)
    states, edges, quiescent, stuck = _explore(config, traces)
    ok = _can_reach(len(states), edges, quiescent) if quiescent else set()
    doomed = set(range(len(states))) - ok
    assert doomed, (
        "expected the drop policy to be unsound on this workload; if "
        "this starts passing the protocol semantics changed — update "
        "SURVEY.md §6.3"
    )
    # both workloads also reach TERMINAL deadlocks (waiting node, all
    # mailboxes empty) — the claim README makes, asserted so it cannot
    # silently rot
    assert stuck, "expected terminal non-quiescent states under drop"
    assert stuck <= doomed
    for si in doomed:
        fr = states[si]
        waiting_somewhere = any(f[3] for f in fr) or any(
            f[6] for f in fr
        )
        assert waiting_somewhere, (
            f"doomed state {si} without a waiting node or in-flight "
            "message — not the documented livelock signature"
        )


