"""Data-parallel multi-device execution of the Pallas fast path.

``DataShardedPallasEngine`` splits the ensemble (trailing lane axis)
across local devices with ``shard_map`` — each shard runs the whole
segment-loop program independently, so the per-cycle hot loop must
contain ZERO cross-shard collectives (the one permitted cross-shard op
is the final status reduce, once per run, outside the loop).  The
acceptance bar is bit-exactness: every state plane, cycle count, and
node dump identical to the single-device engine, across the streaming
/ legacy / windowed / ungated variants.

Runs on the virtual 8-device CPU mesh from conftest; skipped cleanly
when the device-count flag could not take effect.
"""

import jax
import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.parallel.sharding import (
    DataShardedPallasEngine,
    make_data_mesh,
)
from hpa2_tpu.utils.trace import gen_uniform_random_arrays

pytestmark = pytest.mark.virtual_mesh

ROBUST = Semantics().robust()


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


def _assert_bit_exact(shd, ref):
    for f, v in ref.state.items():
        assert np.array_equal(np.asarray(v), np.asarray(shd.state[f])), (
            f"state plane {f!r} diverged under data sharding"
        )
    assert shd.cycle == ref.cycle
    assert shd.instructions == ref.instructions
    assert shd.messages == ref.messages
    assert shd.stats() == ref.stats()
    for s in {0, ref.b // 2, ref.b - 1}:
        assert _dicts(shd.system_final_dumps(s)) == _dicts(
            ref.system_final_dumps(s)
        ), f"node dumps diverged for system {s}"


# engine-kwarg variants: every run-program shape the engine can take
# (full-trace, windowed with a ragged tail, single-cycle windows, the
# legacy non-streaming program, and the ungated kernel)
_VARIANTS = {
    "default": dict(),
    "window7": dict(trace_window=7, snapshots=False),
    "window1": dict(trace_window=1, snapshots=False),
    "legacy": dict(stream=False),
    "nogate": dict(gate=False),
}


@pytest.mark.parametrize("variant", sorted(_VARIANTS),
                         ids=sorted(_VARIANTS))
def test_sharded_bit_exact_vs_single_device(variant):
    _require_devices(8)
    kw = dict(block=8, cycles_per_call=32, **_VARIANTS[variant])
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 32, 20, seed=2)
    ref = PallasEngine(cfg, *arrays, **kw).run(max_cycles=200_000)
    shd = DataShardedPallasEngine(
        cfg, *arrays, data_shards=8, **kw
    ).run(max_cycles=200_000)
    assert shd.data_shards == 8
    _assert_bit_exact(shd, ref)


def test_sharded_bit_exact_bench_workload():
    """The bench.py workload shape (8-node robust systems, capped
    mailboxes, windowed traces) — the configuration the MULTICHIP
    artifact measures."""
    _require_devices(8)
    cfg = SystemConfig(num_procs=8, msg_buffer_size=16,
                       semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 64, 24, seed=0)
    kw = dict(block=64, cycles_per_call=64, snapshots=False,
              trace_window=8)
    ref = PallasEngine(cfg, *arrays, **kw).run(max_cycles=500_000)
    shd = DataShardedPallasEngine(
        cfg, *arrays, data_shards=8, **kw
    ).run(max_cycles=500_000)
    _assert_bit_exact(shd, ref)


def test_fewer_shards_than_devices():
    _require_devices(8)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 24, 16, seed=5)
    ref = PallasEngine(cfg, *arrays, block=8).run()
    shd = DataShardedPallasEngine(
        cfg, *arrays, data_shards=2, block=8
    ).run()
    assert shd.data_shards == 2
    _assert_bit_exact(shd, ref)


# -- operand placement ------------------------------------------------


def test_state_planes_sharded_on_distinct_devices():
    _require_devices(8)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 32, 8, seed=1)
    eng = DataShardedPallasEngine(cfg, *arrays, data_shards=8, block=4)
    for f, v in eng.state.items():
        shards = v.addressable_shards
        assert len(shards) == 8, f"{f}: expected 8 shards"
        assert len({s.device for s in shards}) == 8, (
            f"{f}: shards must land on distinct devices"
        )
        for s in shards:
            # only the trailing lane axis splits: each device owns b/8
            assert s.data.shape == v.shape[:-1] + (v.shape[-1] // 8,)
    # the streamed trace planes split the same way ([N,T,B] / [N,B])
    for arr in (eng._tr_full, eng._tr_len_full):
        shards = arr.addressable_shards
        assert len(shards) == 8
        assert all(
            s.data.shape == arr.shape[:-1] + (arr.shape[-1] // 8,)
            for s in shards
        )


def test_batch_not_divisible_raises():
    _require_devices(8)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 30, 8, seed=1)
    with pytest.raises(ValueError, match="divisible"):
        DataShardedPallasEngine(cfg, *arrays, data_shards=8)


def test_rejects_foreign_mesh_axis():
    _require_devices(2)
    from jax.sharding import Mesh

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 16, 8, seed=1)
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    with pytest.raises(ValueError):
        DataShardedPallasEngine(cfg, *arrays, mesh=mesh)


def test_make_data_mesh_bounds():
    with pytest.raises(ValueError):
        make_data_mesh(0)
    with pytest.raises(ValueError):
        make_data_mesh(len(jax.devices()) + 1)
    mesh = make_data_mesh()
    assert tuple(mesh.axis_names) == ("data",)
    assert mesh.shape["data"] == len(jax.devices())


# -- collective-free hot loop (jaxpr layer) ---------------------------
#
# The traversal and primitive lists live in hpa2_tpu/analysis/ir.py
# (one walker for the whole repo); the same properties are enforced by
# the checked-in `data-sharded-pallas` contract.


@pytest.mark.parametrize("stream", [True, False],
                         ids=["stream", "legacy"])
def test_shard_body_has_no_collectives(stream):
    """The per-shard program (everything under shard_map) must be
    collective-free: each shard's whole run — block grid, prefetch,
    quiescence loop — is independent.  The status reduce lives outside
    the shard_map."""
    from hpa2_tpu.analysis import ir

    _require_devices(8)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 32, 8, seed=1)
    eng = DataShardedPallasEngine(
        cfg, *arrays, data_shards=8, block=4, stream=stream
    )
    jx = jax.make_jaxpr(eng._runner(10_000))(
        eng.state, eng._tr_full, eng._tr_len_full
    )
    bodies = ir.find_subjaxprs(jx.jaxpr, "shard_map")
    assert bodies, "sharded runner lost its shard_map"
    assert any(
        ir.count_prims(b, ("pallas_call",)) for b in bodies
    ), "shard body lost its pallas_call"
    for body in bodies:
        n = ir.count_prims(body, ir.COLLECTIVE_PRIMS)
        assert n == 0, (
            f"{n} collective op(s) inside the per-shard run program"
        )


# -- collective-free cycle body (compiled-HLO layer) ------------------


def test_compiled_hlo_loop_body_collective_free():
    """Pin the zero-collectives property at the artifact the device
    actually executes: no all-reduce / all-gather / collective-permute
    / all-to-all / reduce-scatter anywhere in the transitive closure
    of the compiled while loops.  (The final status reduce compiles to
    an all-reduce in ENTRY — outside every loop — which this guard
    deliberately permits.)"""
    from hpa2_tpu.analysis import ir

    _require_devices(8)
    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    arrays = gen_uniform_random_arrays(cfg, 32, 8, seed=1)
    eng = DataShardedPallasEngine(cfg, *arrays, data_shards=8, block=4)
    text = eng.lower_run(10_000).compile().as_text()

    comps = ir.hlo_computations(text)
    assert ir.hlo_loop_closure(comps, text), (
        "compiled module has no while loops to guard"
    )

    offenders = ir.hlo_loop_collectives(text)
    assert not offenders, (
        "collective(s) inside the compiled cycle loop:\n"
        + "\n".join(f"  {n}: {ln}" for n, ln in offenders[:8])
    )
