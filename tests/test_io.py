"""I/O layer tests: trace parsing and byte-exact dump formatting.

The strongest formatter test available without an engine: parse every
shipped fixture dump back into structured state and re-format it — the
result must equal the fixture byte for byte (SURVEY.md §7.2 step 1
gate).
"""

import glob
import os

import pytest

from hpa2_tpu.config import SystemConfig
from hpa2_tpu.models.protocol import CacheState, DirState, Instr
from hpa2_tpu.utils.dump import format_processor_state, parse_processor_dump
from hpa2_tpu.utils.trace import (
    load_core_trace,
    load_instruction_order,
    load_trace_dir,
    parse_core_trace,
    validate_order_against_traces,
)

CONFIG = SystemConfig()


def all_fixture_dumps(root):
    pats = [
        os.path.join(root, "*", "core_*_output.txt"),
        os.path.join(root, "*", "run_*", "core_*_output.txt"),
    ]
    paths = sorted(p for pat in pats for p in glob.glob(str(pat)))
    assert paths, "no fixture dumps found"
    return paths


def test_fixture_dump_roundtrip_byte_exact(reference_tests_dir):
    paths = all_fixture_dumps(reference_tests_dir)
    assert len(paths) >= 36  # 3 single-run suites + 2 + 4 run sets, 4 nodes each
    for path in paths:
        with open(path, "r") as f:
            text = f.read()
        dump = parse_processor_dump(text)
        regen = format_processor_state(dump, CONFIG)
        assert regen == text, f"round-trip mismatch for {path}"


def test_parse_sample_trace(reference_tests_dir):
    instrs = load_core_trace(str(reference_tests_dir / "sample" / "core_0.txt"))
    assert instrs == [Instr("W", 0x15, 100), Instr("R", 0x17)]
    empty = load_core_trace(str(reference_tests_dir / "sample" / "core_2.txt"))
    assert empty == []


def test_trace_value_wraps_like_sscanf_hhu():
    assert parse_core_trace("WR 0x05 300")[0].value == 300 % 256


def test_trace_cap_matches_reference():
    text = "\n".join(f"RD 0x0{i % 10}" for i in range(40))
    assert len(parse_core_trace(text, max_instr=32)) == 32


def test_malformed_trace_rejected():
    with pytest.raises(ValueError):
        parse_core_trace("RD 0x05\nBOGUS LINE\n")


def test_orders_are_valid_interleavings(reference_tests_dir):
    suites = {
        "sample": [str(reference_tests_dir / "sample" / "instruction_order.txt")],
        "test_1": [str(reference_tests_dir / "test_1" / "instruction_order.txt")],
        "test_2": [str(reference_tests_dir / "test_2" / "instruction_order.txt")],
        "test_3": sorted(
            glob.glob(str(reference_tests_dir / "test_3" / "run_*" / "instruction_order.txt"))
        ),
        "test_4": sorted(
            glob.glob(str(reference_tests_dir / "test_4" / "run_*" / "instruction_order.txt"))
        ),
    }
    for suite, order_paths in suites.items():
        traces = load_trace_dir(str(reference_tests_dir / suite), CONFIG)
        assert order_paths, suite
        for path in order_paths:
            order = load_instruction_order(path)
            validate_order_against_traces(order, traces)


def test_dump_parser_fields(reference_tests_dir):
    with open(reference_tests_dir / "sample" / "core_1_output.txt") as f:
        d = parse_processor_dump(f.read())
    assert d.proc_id == 1
    # node 1's memory[5] is address 0x15: P0's write of 100 reached it
    # via the WRITEBACK_INT/FLUSH intervention when P1 later read 0x15.
    assert d.memory[5] == 100
    assert d.dir_state[5] == DirState.S and d.dir_sharers[5] == 0b11
    assert d.dir_state[7] == DirState.EM and d.dir_sharers[7] == 0b1
    assert d.cache_addr[1] == 0x15 and d.cache_value[1] == 100
    assert d.cache_state[1] == CacheState.SHARED
