"""Sanitizer smoke tests for the native backend.

Runs the lockstep and OpenMP engines under ASan+UBSan (and the OMP
engine under TSan when available) on a small synthetic workload.  The
engines index by node id, cache line, and block from message fields in
a hot loop — exactly the code a fuzzed or mutated message would push
out of bounds — so a clean sanitizer pass is a real property, not a
formality.

Skips (never fails) when the sanitizer toolchain is unavailable: the
compiler may lack libasan/libtsan in minimal containers.
"""

import os
import subprocess

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")


def _build(target: str, binary: str):
    """Build a sanitizer binary; skip the test if the toolchain can't."""
    proc = subprocess.run(
        ["make", "-C", NATIVE_DIR, target],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        pytest.skip(f"sanitizer build unavailable: {proc.stderr[-300:]}")
    path = os.path.join(NATIVE_DIR, "build", binary)
    if not os.path.exists(path):
        pytest.skip(f"sanitizer binary missing after build: {binary}")
    return path


def _run(binary: str, args, env_extra):
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.run(
        [binary] + args, capture_output=True, text=True, env=env,
        timeout=300,
    )
    # a sanitizer report is always accompanied by a nonzero exit
    # (abort_on_error / halt_on_error below), so rc==0 means clean
    assert proc.returncode == 0, (
        f"sanitizer run failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-1000:]}\n{proc.stderr[-2000:]}"
    )


@pytest.mark.parametrize("mode", ["lockstep", "omp"])
def test_asan_ubsan_bench(mode):
    binary = _build("asan", "hpa2sim_asan")
    _run(
        binary,
        # --robust: the default drop policy faithfully livelocks on
        # random workloads (its documented hang), which would hit the
        # cycle budget rather than exercise the memory paths
        ["--bench", "300", "--mode", mode, "--robust", "--json",
         "--seed", "7"],
        {
            # libgomp's persistent thread pool reads as a leak; the
            # target here is heap/stack corruption and UB, not leaks
            "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
            "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
        },
    )


def test_asan_ubsan_robust_quirks():
    """The quirk/robust code paths index differently (NACK re-serve,
    overloaded notify) — cover them under the sanitizers too."""
    binary = _build("asan", "hpa2sim_asan")
    _run(
        binary,
        ["--bench", "200", "--robust", "--json"],
        {"ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
         "UBSAN_OPTIONS": "halt_on_error=1"},
    )
    # eager-write + flush-old-fill only: the overloaded-notify quirk
    # faithfully livelocks on random workloads (SURVEY.md §6.3)
    _run(
        binary,
        ["--bench", "200", "--robust", "--quirk", "eager-write",
         "--quirk", "flush-old-fill", "--json"],
        {"ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
         "UBSAN_OPTIONS": "halt_on_error=1"},
    )


@pytest.mark.slow
def test_tsan_omp_bench():
    """TSan over the free-running OpenMP engine (ring mailboxes under
    per-node locks).  Slow: TSan is a ~10x slowdown."""
    binary = _build("tsan", "hpa2sim_tsan")
    _run(
        binary,
        ["--bench", "200", "--mode", "omp", "--robust", "--json"],
        {"TSAN_OPTIONS": "halt_on_error=1"},
    )


@pytest.mark.slow
def test_tsan_omp_oversubscribed():
    """TSan with 2x-cores OMP threads driving a 32-node system: the
    scheduler preempts threads mid-protocol-step, widening the
    interleaving space far beyond the free-running default (where one
    thread per node mostly runs unpreempted).  Races that need an
    unlucky preemption point — e.g. between a mailbox ring index read
    and its guarded write — surface here or nowhere."""
    binary = _build("tsan", "hpa2sim_tsan")
    threads = 2 * (os.cpu_count() or 4)
    _run(
        binary,
        ["--bench", "1500", "--mode", "omp", "--nodes", "32",
         "--threads", str(threads), "--robust", "--json",
         "--seed", "11"],
        {"TSAN_OPTIONS": "halt_on_error=1"},
    )
