"""Node-axis sharding for the Pallas fast path: one system's node
planes split into contiguous blocks over the mesh's ``node`` axis,
with phase-C delivery running as the targeted cross-shard exchange
(ops/exchange.py) at the XLA level.

Everything here must be *bit-identical* to the single-chip engines —
same state planes, same counters, same per-node dumps — for EVERY
``exchange_mode``, and the cycle loop must contain only the plan's
collectives (``exchange.plan_collectives``: one batched ``all_to_all``
each way by default) plus ONE stacked counter psum and ONE telemetry
pmax per cycle, no per-cycle ``all_gather``.

Runs on the virtual 8-device CPU mesh from conftest.  The interpret-
mode single-chip references dominate the wall clock, so they are
shared across tests via module-level caches.
"""

import dataclasses
import functools

import jax
import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.parallel.sharding import (
    NodeShardedEngine,
    NodeShardedPallasEngine,
    make_mesh,
)
from hpa2_tpu.utils.trace import (
    gen_uniform_random,
    gen_uniform_random_arrays,
    traces_to_arrays,
)

pytestmark = pytest.mark.virtual_mesh

ROBUST = Semantics().robust()


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _cfg(n=8):
    return SystemConfig(num_procs=n, semantics=ROBUST)


@functools.lru_cache(maxsize=None)
def _arrays(n=8, bb=4, t=12, seed=1):
    return gen_uniform_random_arrays(_cfg(n), bb, t, seed=seed)


@functools.lru_cache(maxsize=None)
def _ref(n=8, bb=4, t=12, seed=1, snapshots=True):
    """The single-chip interpret-mode reference (expensive: interpret
    runs the whole kernel through the Pallas evaluator)."""
    return PallasEngine(
        _cfg(n), *_arrays(n, bb, t, seed), interpret=True,
        block=max(bb // 2, 1), snapshots=snapshots,
    ).run()


def _assert_bit_exact(shd, ref):
    for f, v in ref.state.items():
        assert np.array_equal(np.asarray(v), np.asarray(shd.state[f])), (
            f"state plane {f!r} diverged under node sharding"
        )
    assert shd.cycle == ref.cycle
    assert shd.instructions == ref.instructions
    assert shd.messages == ref.messages
    # the sharded run reports the exchange telemetry block on top of
    # the (byte-identical) architectural counters
    shd_stats = {
        k: v for k, v in shd.stats().items()
        if not k.startswith("exchange_")
    }
    assert shd_stats == ref.stats()
    for s in range(ref.b):
        assert [d.__dict__ for d in shd.system_final_dumps(s)] == [
            d.__dict__ for d in ref.system_final_dumps(s)
        ], f"node dumps diverged for system {s}"


# -- bit-exactness vs the single-chip kernel --------------------------


@pytest.mark.parametrize(
    "node_shards,data_shards", [(2, 1), (4, 2)],
    ids=["1x2", "2x4"],
)
def test_bit_exact_vs_single_device(node_shards, data_shards):
    """data x node mesh, snapshots ON: every plane (including the
    snapshot planes) and every per-node dump byte-identical."""
    _require_devices(node_shards * data_shards)
    ref = _ref()
    shd = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=node_shards,
        data_shards=data_shards, cycles_per_call=16,
    ).run()
    assert shd.node_shards == node_shards
    assert shd.data_shards == data_shards
    _assert_bit_exact(shd, ref)
    assert shd.cross_shard_msgs > 0, (
        "uniform-random traffic must cross shards"
    )
    stats = shd.stats()
    assert stats["exchange_sent"] == shd.cross_shard_msgs
    assert stats["exchange_slot_hwm"] >= 1
    assert stats["exchange_bytes_per_cycle"] > 0


@pytest.mark.parametrize("mode", ["pairwise", "butterfly", "hier"])
def test_bit_exact_every_exchange_mode(mode):
    """``a2a`` (the default) is exercised by every test above; the
    alternative collective schedules must keep every plane and dump
    byte-identical too — the transport plan only changes HOW entries
    travel, never what arrives."""
    _require_devices(4)
    ref = _ref()
    cfg = dataclasses.replace(_cfg(), exchange_mode=mode)
    shd = NodeShardedPallasEngine(
        cfg, *_arrays(), node_shards=4, cycles_per_call=16,
    ).run()
    _assert_bit_exact(shd, ref)


def test_bit_exact_4x2_mesh_snapshots_off():
    """The transposed mesh (data_shards=4, node_shards=2) without
    snapshot planes."""
    _require_devices(8)
    ref = _ref(snapshots=False)
    shd = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=2, data_shards=4,
        snapshots=False, cycles_per_call=16,
    ).run()
    _assert_bit_exact(shd, ref)


def test_bit_exact_split_plane_22_nodes():
    """num_procs=22 > 21 flips the sharer planes into split multi-word
    mode; the exchange masks/feedback are per-word.  Cross-backend:
    the jax lockstep engine is the reference for the dumps."""
    _require_devices(2)
    cfg = _cfg(22)
    batch = [gen_uniform_random(cfg, 10, seed=40 + s) for s in range(2)]
    shd = NodeShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, batch), node_shards=2,
        snapshots=False, cycles_per_call=16,
    ).run()
    for s, traces in enumerate(batch):
        ref = JaxEngine(cfg, traces).run()
        assert [d.__dict__ for d in shd.system_final_dumps(s)] == [
            d.__dict__ for d in ref.final_dumps()
        ], f"dumps diverged vs jax engine for system {s}"


def test_cross_backend_dumps_vs_jax_and_node_sharded():
    """The sharded Pallas path, the single-chip jax engine and the
    node-sharded jax engine (ops/step.py exchange retrofit) all agree
    on the final per-node dumps."""
    _require_devices(8)
    cfg = _cfg()
    traces = gen_uniform_random(cfg, 12, seed=7)
    shd = NodeShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, [traces]), node_shards=4,
        snapshots=False, cycles_per_call=16,
    ).run()
    jx = JaxEngine(cfg, traces).run()
    nsx = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    ).run()
    want = [d.__dict__ for d in jx.final_dumps()]
    assert [d.__dict__ for d in shd.system_final_dumps(0)] == want
    assert [d.__dict__ for d in nsx.final_dumps()] == want


# -- fused occupancy scheduler / packed planes ------------------------


@pytest.mark.parametrize("packed", [False, True], ids=["i32", "packed"])
def test_fused_schedule_bit_exact(packed):
    """The fused occupancy scheduler under node sharding: the sharded
    scheduled run must reproduce the sharded unscheduled run's dumps
    (which test_bit_exact_* pins to the single-chip engine)."""
    _require_devices(8)
    cfg = _cfg()
    arrays = _arrays()
    kw = dict(snapshots=False, cycles_per_call=16, trace_window=8)
    plain = NodeShardedPallasEngine(
        cfg, *arrays, node_shards=4, data_shards=2, **kw
    ).run()
    fused = NodeShardedPallasEngine(
        cfg, *arrays, node_shards=4, data_shards=2,
        schedule=Schedule(), packed=packed, **kw
    ).run()
    assert fused.occupancy.device_programs == 1
    assert fused.occupancy.host_barriers == 0
    for s in range(plain.b):
        assert [d.__dict__ for d in fused.system_final_dumps(s)] == [
            d.__dict__ for d in plain.system_final_dumps(s)
        ], f"fused dumps diverged for system {s}"
    assert fused.instructions == plain.instructions


# -- exchange buffer sizing -------------------------------------------


def test_exchange_slots_overflow_is_loud():
    """A too-small per-peer exchange buffer must fail the whole run
    with a StallError, never drop messages silently — and the message
    must name the worst event: cycle, shard pair, demand vs capacity."""
    _require_devices(2)
    eng = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=2, exchange_slots=1,
        cycles_per_call=16,
    )
    with pytest.raises(StallError, match="exchange overflow") as ei:
        eng.run()
    msg = str(ei.value)
    assert "exchange_slots=1" in msg
    assert "worst cycle" in msg, f"overflow diagnostics missing: {msg}"
    assert "demanded" in msg


# -- geometry validation ----------------------------------------------


def test_geometry_validation():
    _require_devices(4)
    cfg = _cfg()
    arrays = _arrays()
    with pytest.raises(ValueError, match="not divisible by node"):
        NodeShardedPallasEngine(cfg, *arrays, node_shards=3)
    with pytest.raises(ValueError, match="data_shards"):
        NodeShardedPallasEngine(
            cfg, *arrays, node_shards=2, data_shards=3
        )
    with pytest.raises(ValueError, match="unsharded fast path"):
        NodeShardedPallasEngine(cfg, *arrays, node_shards=1)
    with pytest.raises(NotImplementedError, match="fused"):
        NodeShardedPallasEngine(
            cfg, *arrays, node_shards=2,
            schedule=Schedule(fused=False),
        )


# -- collective-count guards (jaxpr layer) ----------------------------
#
# The whole point of the targeted exchange: the cycle loop carries
# exactly the transport plan's collectives (one batched all_to_all
# each way for the default "a2a" mode; 2*(D-1) ppermutes for
# "pairwise"; 2*log2(D) for "butterfly"; 2*(Di+Do-2) for "hier") plus
# ONE stacked counter psum and ONE telemetry pmax, and never an
# all_gather.  Counting primitives in the traced program pins this —
# a regression to gather-the-world delivery or a serial-round relapse
# shows up as all_gather > 0 or a changed collective count.


_MODES = ("pairwise", "a2a", "butterfly", "hier")


@pytest.mark.parametrize("mode", _MODES)
@pytest.mark.parametrize("node_shards", [2, 4])
def test_cycle_loop_collectives_pinned(node_shards, mode):
    from hpa2_tpu.analysis.contracts import measure_node_sharded

    _require_devices(node_shards)
    got = measure_node_sharded("pallas", mode, node_shards).values
    assert got["ppermute"] == got["plan.ppermute"], (
        f"{mode}@{node_shards}: plan ships {got['plan.ppermute']} "
        f"ppermutes, traced {got['ppermute']}"
    )
    assert got["all_to_all"] == got["plan.all_to_all"], (
        f"{mode}@{node_shards}: plan ships {got['plan.all_to_all']} "
        f"all_to_alls, traced {got['all_to_all']}"
    )
    # one stacked counter/quiescence psum in the cycle + the per-
    # segment activity seed psum outside the cycle loop
    assert got["psum"] == 2, (
        f"expected cycle psum + seed psum, got {got['psum']}"
    )
    # in-cycle telemetry pmax (slot hwm + overflow diagnostics) + the
    # whole-mesh loop gate traced twice (while seed and loop body)
    assert got["pmax"] == 3, (
        f"expected telemetry + seed + loop-gate pmax, got {got['pmax']}"
    )
    assert got["gather"] == 0, (
        f"{got['gather']} gather-the-world collective(s) crept back "
        "into the node-sharded run program"
    )


# -- multicast INV fan-out --------------------------------------------
#
# An invalidation to S sharers living on the same remote shard ships
# as ONE exchange entry carrying the sharer bitmask and expands
# shard-locally — exchange_multicast_saved counts the entries NOT
# shipped (fan - 1 per multicast entry).


def test_multicast_expansion_hand_computed():
    """num_procs=4 over 2 shards: nodes 2 and 3 (both on shard 1) read
    addr 0 (homed at node 0 on shard 0); after the sharers are
    registered, node 0 writes it.  The home's invalidation to sharers
    {2, 3} crosses the shard boundary as ONE bitmask entry that
    expands to two deliveries — exactly one shipped entry saved."""
    _require_devices(2)
    from hpa2_tpu.models.protocol import Instr

    cfg = SystemConfig(num_procs=4, semantics=ROBUST)
    filler = [Instr("R", 16 + 1, 0)] * 12  # node 1's own home block
    traces = [
        [Instr("R", 1, 0)] * 12 + [Instr("W", 0, 77)],  # home writes last
        list(filler),
        [Instr("R", 0, 0)],
        [Instr("R", 0, 0)],
    ]
    shd = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=2)
    ).run()
    ref = JaxEngine(cfg, traces).run()
    assert [d.__dict__ for d in shd.final_dumps()] == [
        d.__dict__ for d in ref.final_dumps()
    ]
    assert shd.stats()["exchange_multicast_saved"] == 1, shd.stats()


@pytest.mark.parametrize("mode", ["a2a", "hier"])
def test_invalidation_storm_multicast_savings(mode):
    """Every node repeatedly reads a block homed at node 0, then the
    home rewrites it: each rewrite fans an INV to sharers on every
    remote shard, so the bitmask encoding must save real traffic
    (exchange_multicast_saved > 0) while dumps stay bit-identical."""
    _require_devices(4)
    from hpa2_tpu.models.protocol import Instr

    cfg = SystemConfig(num_procs=8, semantics=ROBUST)
    reads = 3
    traces = [[] for _ in range(8)]
    for rnd in range(3):
        addr = rnd  # all homed at node 0
        for i in range(1, 8):
            traces[i] += [Instr("R", addr, 0)] * reads
        traces[0] += [Instr("R", 16, 0)] * (reads * 3) + [
            Instr("W", addr, 100 + rnd)
        ]
    cfg = dataclasses.replace(cfg, exchange_mode=mode)
    shd = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    ).run()
    ref = JaxEngine(cfg, traces).run()
    assert [d.__dict__ for d in shd.final_dumps()] == [
        d.__dict__ for d in ref.final_dumps()
    ]
    assert shd.cycle == ref.cycle
    stats = shd.stats()
    assert stats["exchange_multicast_saved"] > 0, stats
    # the storm also exercises the Pallas path's expansion
    from hpa2_tpu.utils.trace import traces_to_arrays

    pshd = NodeShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, [traces]), node_shards=4,
        snapshots=False, cycles_per_call=16,
    ).run()
    assert [d.__dict__ for d in pshd.system_final_dumps(0)] == [
        d.__dict__ for d in ref.final_dumps()
    ]
    assert pshd.stats()["exchange_multicast_saved"] > 0


@pytest.mark.parametrize("mode", _MODES)
def test_jax_step_collectives_pinned(mode):
    """Same pin for the retrofitted ops/step.py path: the sharded step
    carries exactly the plan's collectives + 1 stacked counter psum
    (+ the elision fast-forward's progress psum — elide defaults on)
    + 1 telemetry pmax, no all_gather."""
    from hpa2_tpu.analysis.contracts import measure_node_sharded

    _require_devices(4)
    got = measure_node_sharded("jax", mode, 4).values
    assert got["ppermute"] == got["plan.ppermute"]
    assert got["all_to_all"] == got["plan.all_to_all"]
    assert got["psum"] == 2
    assert got["pmax"] == 1
    assert got["gather"] == 0
