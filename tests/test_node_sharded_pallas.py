"""Node-axis sharding for the Pallas fast path: one system's node
planes split into contiguous blocks over the mesh's ``node`` axis,
with phase-C delivery running as the targeted cross-shard exchange
(ops/exchange.py) at the XLA level.

Everything here must be *bit-identical* to the single-chip engines —
same state planes, same counters, same per-node dumps — and the cycle
loop must contain only the exchange collectives: ``2*(D-1)`` ppermutes
plus ONE stacked counter psum per cycle, no per-cycle ``all_gather``.

Runs on the virtual 8-device CPU mesh from conftest.  The interpret-
mode single-chip references dominate the wall clock, so they are
shared across tests via module-level caches.
"""

import functools

import jax
import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import StallError
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.parallel.sharding import (
    NodeShardedEngine,
    NodeShardedPallasEngine,
    make_mesh,
)
from hpa2_tpu.utils.trace import (
    gen_uniform_random,
    gen_uniform_random_arrays,
    traces_to_arrays,
)

pytestmark = pytest.mark.virtual_mesh

ROBUST = Semantics().robust()


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _cfg(n=8):
    return SystemConfig(num_procs=n, semantics=ROBUST)


@functools.lru_cache(maxsize=None)
def _arrays(n=8, bb=4, t=12, seed=1):
    return gen_uniform_random_arrays(_cfg(n), bb, t, seed=seed)


@functools.lru_cache(maxsize=None)
def _ref(n=8, bb=4, t=12, seed=1, snapshots=True):
    """The single-chip interpret-mode reference (expensive: interpret
    runs the whole kernel through the Pallas evaluator)."""
    return PallasEngine(
        _cfg(n), *_arrays(n, bb, t, seed), interpret=True,
        block=max(bb // 2, 1), snapshots=snapshots,
    ).run()


def _assert_bit_exact(shd, ref):
    for f, v in ref.state.items():
        assert np.array_equal(np.asarray(v), np.asarray(shd.state[f])), (
            f"state plane {f!r} diverged under node sharding"
        )
    assert shd.cycle == ref.cycle
    assert shd.instructions == ref.instructions
    assert shd.messages == ref.messages
    assert shd.stats() == ref.stats()
    for s in range(ref.b):
        assert [d.__dict__ for d in shd.system_final_dumps(s)] == [
            d.__dict__ for d in ref.system_final_dumps(s)
        ], f"node dumps diverged for system {s}"


# -- bit-exactness vs the single-chip kernel --------------------------


@pytest.mark.parametrize(
    "node_shards,data_shards", [(2, 1), (4, 2)],
    ids=["1x2", "2x4"],
)
def test_bit_exact_vs_single_device(node_shards, data_shards):
    """data x node mesh, snapshots ON: every plane (including the
    snapshot planes) and every per-node dump byte-identical."""
    _require_devices(node_shards * data_shards)
    ref = _ref()
    shd = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=node_shards,
        data_shards=data_shards, cycles_per_call=16,
    ).run()
    assert shd.node_shards == node_shards
    assert shd.data_shards == data_shards
    _assert_bit_exact(shd, ref)
    assert shd.cross_shard_msgs > 0, (
        "uniform-random traffic must cross shards"
    )


def test_bit_exact_4x2_mesh_snapshots_off():
    """The transposed mesh (data_shards=4, node_shards=2) without
    snapshot planes."""
    _require_devices(8)
    ref = _ref(snapshots=False)
    shd = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=2, data_shards=4,
        snapshots=False, cycles_per_call=16,
    ).run()
    _assert_bit_exact(shd, ref)


def test_bit_exact_split_plane_22_nodes():
    """num_procs=22 > 21 flips the sharer planes into split multi-word
    mode; the exchange masks/feedback are per-word.  Cross-backend:
    the jax lockstep engine is the reference for the dumps."""
    _require_devices(2)
    cfg = _cfg(22)
    batch = [gen_uniform_random(cfg, 10, seed=40 + s) for s in range(2)]
    shd = NodeShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, batch), node_shards=2,
        snapshots=False, cycles_per_call=16,
    ).run()
    for s, traces in enumerate(batch):
        ref = JaxEngine(cfg, traces).run()
        assert [d.__dict__ for d in shd.system_final_dumps(s)] == [
            d.__dict__ for d in ref.final_dumps()
        ], f"dumps diverged vs jax engine for system {s}"


def test_cross_backend_dumps_vs_jax_and_node_sharded():
    """The sharded Pallas path, the single-chip jax engine and the
    node-sharded jax engine (ops/step.py exchange retrofit) all agree
    on the final per-node dumps."""
    _require_devices(8)
    cfg = _cfg()
    traces = gen_uniform_random(cfg, 12, seed=7)
    shd = NodeShardedPallasEngine(
        cfg, *traces_to_arrays(cfg, [traces]), node_shards=4,
        snapshots=False, cycles_per_call=16,
    ).run()
    jx = JaxEngine(cfg, traces).run()
    nsx = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    ).run()
    want = [d.__dict__ for d in jx.final_dumps()]
    assert [d.__dict__ for d in shd.system_final_dumps(0)] == want
    assert [d.__dict__ for d in nsx.final_dumps()] == want


# -- fused occupancy scheduler / packed planes ------------------------


@pytest.mark.parametrize("packed", [False, True], ids=["i32", "packed"])
def test_fused_schedule_bit_exact(packed):
    """The fused occupancy scheduler under node sharding: the sharded
    scheduled run must reproduce the sharded unscheduled run's dumps
    (which test_bit_exact_* pins to the single-chip engine)."""
    _require_devices(8)
    cfg = _cfg()
    arrays = _arrays()
    kw = dict(snapshots=False, cycles_per_call=16, trace_window=8)
    plain = NodeShardedPallasEngine(
        cfg, *arrays, node_shards=4, data_shards=2, **kw
    ).run()
    fused = NodeShardedPallasEngine(
        cfg, *arrays, node_shards=4, data_shards=2,
        schedule=Schedule(), packed=packed, **kw
    ).run()
    assert fused.occupancy.device_programs == 1
    assert fused.occupancy.host_barriers == 0
    for s in range(plain.b):
        assert [d.__dict__ for d in fused.system_final_dumps(s)] == [
            d.__dict__ for d in plain.system_final_dumps(s)
        ], f"fused dumps diverged for system {s}"
    assert fused.instructions == plain.instructions


# -- exchange buffer sizing -------------------------------------------


def test_exchange_slots_overflow_is_loud():
    """A too-small per-peer exchange buffer must fail the whole run
    with a StallError, never drop messages silently."""
    _require_devices(2)
    eng = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=2, exchange_slots=1,
        cycles_per_call=16,
    )
    with pytest.raises(StallError, match="exchange overflow"):
        eng.run()


# -- geometry validation ----------------------------------------------


def test_geometry_validation():
    _require_devices(4)
    cfg = _cfg()
    arrays = _arrays()
    with pytest.raises(ValueError, match="not divisible by node"):
        NodeShardedPallasEngine(cfg, *arrays, node_shards=3)
    with pytest.raises(ValueError, match="data_shards"):
        NodeShardedPallasEngine(
            cfg, *arrays, node_shards=2, data_shards=3
        )
    with pytest.raises(ValueError, match="unsharded fast path"):
        NodeShardedPallasEngine(cfg, *arrays, node_shards=1)
    with pytest.raises(NotImplementedError, match="fused"):
        NodeShardedPallasEngine(
            cfg, *arrays, node_shards=2,
            schedule=Schedule(fused=False),
        )


# -- collective-count guards (jaxpr layer) ----------------------------
#
# The whole point of the targeted exchange: the cycle loop carries
# exactly 2*(D-1) ppermutes (forward buffers + acceptance feedback)
# plus ONE stacked counter psum, and never an all_gather.  Counting
# primitives in the traced program pins this — a regression to
# gather-the-world delivery shows up as all_gather > 0 or a changed
# ppermute count.


def _subvalues(eqn):
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr"):
                yield x.jaxpr
            elif hasattr(x, "eqns"):
                yield x


def _find_subjaxprs(jaxpr, prim_name):
    found = []
    for eqn in jaxpr.eqns:
        subs = list(_subvalues(eqn))
        if eqn.primitive.name == prim_name:
            found += subs
        else:
            for sub in subs:
                found += _find_subjaxprs(sub, prim_name)
    return found


def _count_prims(jaxpr, names):
    n = sum(1 for eqn in jaxpr.eqns if eqn.primitive.name in names)
    for eqn in jaxpr.eqns:
        for sub in _subvalues(eqn):
            n += _count_prims(sub, names)
    return n


_PSUM_PRIMS = ("psum", "psum2", "psum_invariant")
_GATHER_PRIMS = ("all_gather", "all_to_all", "all_gather_invariant")


@pytest.mark.parametrize("node_shards", [2, 4])
def test_cycle_loop_collectives_pinned(node_shards):
    _require_devices(node_shards)
    eng = NodeShardedPallasEngine(
        _cfg(), *_arrays(), node_shards=node_shards,
        cycles_per_call=16,
    )
    jx = jax.make_jaxpr(eng._runner(10_000))(
        eng.state, eng._tr_full, eng._tr_len_full
    ).jaxpr
    bodies = _find_subjaxprs(jx, "shard_map")
    assert bodies, "node-sharded runner lost its shard_map"
    n_permute = sum(_count_prims(b, ("ppermute",)) for b in bodies)
    n_psum = sum(_count_prims(b, _PSUM_PRIMS) for b in bodies)
    n_pmax = sum(_count_prims(b, ("pmax",)) for b in bodies)
    n_gather = sum(_count_prims(b, _GATHER_PRIMS) for b in bodies)
    assert n_permute == 2 * (node_shards - 1), (
        f"cycle must ship {2 * (node_shards - 1)} ppermutes "
        f"(fwd + feedback per peer round), found {n_permute}"
    )
    # one stacked counter/quiescence psum in the cycle + the per-
    # segment activity seed psum outside the cycle loop
    assert n_psum == 2, f"expected cycle psum + seed psum, got {n_psum}"
    # the whole-mesh loop gate: one pmax per k-cycle call, outside the
    # cycle loop (traced twice: the while seed and the loop body)
    assert n_pmax == 2, f"expected seed + per-call loop-gate pmax, got {n_pmax}"
    assert n_gather == 0, (
        f"{n_gather} gather-the-world collective(s) crept back into "
        "the node-sharded run program"
    )


def test_jax_step_collectives_pinned():
    """Same pin for the retrofitted ops/step.py path: the sharded step
    function carries 2*(D-1) ppermutes + 1 psum, no all_gather."""
    _require_devices(4)
    cfg = _cfg()
    traces = gen_uniform_random(cfg, 12, seed=7)
    eng = NodeShardedEngine(
        cfg, traces, mesh=make_mesh(node_shards=4)
    )
    jx = jax.make_jaxpr(eng._run)(eng.state).jaxpr
    bodies = _find_subjaxprs(jx, "shard_map")
    assert bodies, "node-sharded jax run lost its shard_map"
    n_permute = sum(_count_prims(b, ("ppermute",)) for b in bodies)
    n_psum = sum(_count_prims(b, _PSUM_PRIMS) for b in bodies)
    n_gather = sum(_count_prims(b, _GATHER_PRIMS) for b in bodies)
    assert n_permute == 2 * 3
    assert n_psum == 1
    assert n_gather == 0
