"""CLI layer (SURVEY.md §7.2 item 5): reference I/O contract — one
trace dir in, ``core_<n>_output.txt`` out (assignment.c:119-123, 831) —
plus backend selection, replay, and the bench subcommand."""

import json

import pytest

from hpa2_tpu.cli import main


@pytest.mark.parametrize("backend", ["spec", "jax", "pallas"])
def test_run_matches_fixtures(tmp_path, backend, reference_tests_dir):
    rc = main([
        "run", str(reference_tests_dir / "test_1"),
        "--backend", backend, "--out", str(tmp_path),
    ])
    assert rc == 0
    for i in range(4):
        got = (tmp_path / f"core_{i}_output.txt").read_text()
        want = (reference_tests_dir / "test_1" / f"core_{i}_output.txt").read_text()
        assert got == want


def test_run_replay(tmp_path, reference_tests_dir):
    suite = reference_tests_dir / "test_3"
    rc = main([
        "run", str(suite), "--backend", "spec",
        "--replay", str(suite / "run_1" / "instruction_order.txt"),
        "--out", str(tmp_path),
    ])
    assert rc == 0
    assert (tmp_path / "core_0_output.txt").exists()


def test_bench_json(tmp_path, capsys):
    rc = main([
        "bench", "--backend", "jax", "--nodes", "4", "--instrs", "16",
        "--batch", "2", "--robust", "--max-instr", "0",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["instrs"] == 4 * 16 * 2
    assert out["ops_per_sec"] > 0


def test_run_omp_backend(tmp_path, reference_tests_dir):
    rc = main([
        "run", str(reference_tests_dir / "test_2"),
        "--backend", "omp", "--out", str(tmp_path),
    ])
    assert rc == 0
    for i in range(4):
        got = (tmp_path / f"core_{i}_output.txt").read_text()
        want = (reference_tests_dir / "test_2" / f"core_{i}_output.txt").read_text()
        assert got == want


def test_run_node_sharded_matches_fixtures(tmp_path, reference_tests_dir):
    """--node-shards on run: the sharded engine is bit-identical to
    the single-chip one, so fixture parity must hold unchanged."""
    rc = main([
        "run", str(reference_tests_dir / "test_1"),
        "--backend", "jax", "--node-shards", "2", "--out", str(tmp_path),
    ])
    assert rc == 0
    for i in range(4):
        got = (tmp_path / f"core_{i}_output.txt").read_text()
        want = (
            reference_tests_dir / "test_1" / f"core_{i}_output.txt"
        ).read_text()
        assert got == want


def test_bench_grid_sharded_json(capsys):
    """--node-shards x --data-shards bench: a sharded ensemble of
    sharded systems over the virtual CPU mesh."""
    rc = main([
        "bench", "--backend", "jax", "--nodes", "8", "--instrs", "8",
        "--batch", "4", "--node-shards", "2", "--data-shards", "2",
        "--robust", "--max-instr", "0",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["instrs"] == 8 * 8 * 4
    assert out["node_shards"] == 2 and out["data_shards"] == 2


def test_shard_flags_rejected_for_non_jax():
    with pytest.raises(SystemExit, match="jax/pallas-backend"):
        main([
            "bench", "--backend", "omp", "--node-shards", "2",
            "--instrs", "8",
        ])
