"""Protocol-compiler gates: MESI bit-exactness, MOESI/MESIF
differential sweeps, directory-format variants, checkpoint round-trips
carrying the owner plane, and loud configuration errors.

The compiled ``ProtocolPlanes`` are the single source of the JAX step,
the Pallas kernel's state constants, and the spec engine's dispatch —
so the gates here are behavioral (spec is the pivot) plus one digest
pin that freezes the lowered MESI planes byte-for-byte: any edit to
the MESI rows that changes the compiled artifact fails loudly instead
of drifting the reference protocol.
"""

import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.models.spec_engine import SpecEngine, StallError
from hpa2_tpu.ops.engine import JaxEngine, engine_stats
from hpa2_tpu.protocols.compiler import planes_for
from hpa2_tpu.utils.trace import gen_uniform_random_arrays

ROBUST = Semantics().robust()

# counters only the event-driven device loop produces; the spec engine
# has no analog, so differential stats comparisons must drop them
_DEVICE_ONLY = {"elided_cycles", "multi_hit_retired"}


def _traces(op, addr, val, b, n):
    return [
        [
            Instr("W", int(a), int(v)) if o == 1 else Instr("R", int(a))
            for o, a, v in zip(op[b, m], addr[b, m], val[b, m])
        ]
        for m in range(n)
    ]


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


def _nonzero(stats):
    return {k: v for k, v in stats.items()
            if v and k not in _DEVICE_ONLY}


def _spec_jax_sweep(cfg, batch, instrs, seed):
    """Spec-vs-JAX dumps, counters, and nonzero stats over ``batch``
    random systems.  Under the default drop policy some seeds livelock
    (stale-intervention drop, SURVEY.md §6.3) — there the engines must
    AGREE on the stall instead of comparing dumps.  Returns summed JAX
    stats (quiesced systems only) for trigger asserts."""
    op, addr, val, length = gen_uniform_random_arrays(
        cfg, batch, instrs, seed=seed
    )
    totals = {}
    for b in range(batch):
        traces = _traces(op, addr, val, b, cfg.num_procs)
        spec = SpecEngine(cfg, traces)
        try:
            spec.run(max_cycles=50_000)
        except StallError:
            with pytest.raises(StallError):
                JaxEngine(cfg, traces, max_cycles=50_000).run()
            continue
        jx = JaxEngine(cfg, traces, max_cycles=50_000)
        jx.run()
        assert _dicts(spec.final_dumps()) == _dicts(jx.final_dumps()), (
            f"b={b}: dumps diverged"
        )
        assert spec.instructions == jx.instructions
        assert spec.messages == jx.messages
        st = engine_stats(jx.state)
        assert _nonzero(spec.stats()) == _nonzero(st), (
            f"b={b}: stats diverged"
        )
        for k, v in st.items():
            totals[k] = totals.get(k, 0) + int(v)
    return totals


# -- MESI bit-exactness ----------------------------------------------------


def test_mesi_planes_digest_pinned():
    """The lowered MESI planes are the reference protocol's compiled
    form; this digest freezes them byte-for-byte.  If an intentional
    table change moves it, re-pin AND re-run the full differential
    suite — an unintentional move is a protocol regression."""
    assert planes_for("mesi", Semantics()).digest() == (
        "10158e4dc973a48cec932b2cadc9c665"
        "18770217695955ea8f099662396f27c0"
    )


@pytest.mark.parametrize("protocol,digest", [
    ("moesi", "d03b9431a7f8910cc20967f8d97be15e"
              "a3ae89ab671c00cb3fb8dc25118d033c"),
    ("mesif", "d33e2b8b87a54a6aff3b0e89577998a7"
              "5b2adec7516fdd7971661e9c23568a71"),
])
def test_variant_planes_digest_pinned(protocol, digest):
    assert planes_for(protocol, Semantics()).digest() == digest


@pytest.mark.parametrize("protocol", ["mesi", "moesi", "mesif"])
def test_planes_semantics_invariant(protocol):
    """State/flag indices must not depend on the semantics knob: the
    Pallas module constants and the dump decoders are built once from
    the default-semantics planes."""
    assert planes_for(protocol, Semantics()).digest() == \
        planes_for(protocol, ROBUST).digest()


# -- MOESI / MESIF spec<->JAX differential sweeps --------------------------


@pytest.mark.sweep
@pytest.mark.parametrize("protocol", ["moesi", "mesif"])
@pytest.mark.parametrize("sem", [Semantics(), ROBUST],
                         ids=["default", "robust"])
def test_protocol_variant_differential(protocol, sem):
    cfg = SystemConfig(num_procs=4, cache_size=4, mem_size=16,
                       msg_buffer_size=64, semantics=sem,
                       protocol=protocol)
    totals = _spec_jax_sweep(cfg, batch=10, instrs=16, seed=77)
    # the variant must actually exercise its distinguishing machinery,
    # or the sweep silently degenerates into a MESI test
    assert totals.get("forwards", 0) > 0 or \
        totals.get("owner_transfers", 0) > 0


@pytest.mark.sweep
def test_moesi_owner_transfers_counted():
    cfg = SystemConfig(num_procs=4, cache_size=4, mem_size=8,
                       msg_buffer_size=64, semantics=ROBUST,
                       protocol="moesi")
    totals = _spec_jax_sweep(cfg, batch=8, instrs=20, seed=3)
    assert totals.get("owner_transfers", 0) > 0


# -- directory-format variants on wide geometries --------------------------
#
# limited:K overflow-to-broadcast and coarse:G coarsening only behave
# differently from the full bitvector when the sharer set outgrows the
# pointer budget / a group spans several nodes — which needs >16-node
# systems with shared hot lines.


def _hot_arrays(cfg, batch, instrs, seed):
    """Uniform traffic biased onto few blocks so sharer sets grow."""
    op, addr, val, length = gen_uniform_random_arrays(
        cfg, batch, instrs, seed=seed
    )
    addr = addr % (3 * cfg.mem_size)  # fold onto the first 3 homes
    return op, addr, val, length


@pytest.mark.sweep
@pytest.mark.parametrize("fmt,counter", [
    ("limited:2", "dir_overflows"),
    ("coarse:4", None),
])
def test_directory_format_differential_18_nodes(fmt, counter):
    cfg = SystemConfig(num_procs=18, cache_size=2, mem_size=8,
                       msg_buffer_size=64, semantics=ROBUST,
                       directory_format=fmt)
    op, addr, val, length = _hot_arrays(cfg, batch=3, instrs=12, seed=9)
    totals = {}
    for b in range(3):
        traces = _traces(op, addr, val, b, cfg.num_procs)
        spec = SpecEngine(cfg, traces)
        spec.run(max_cycles=50_000)
        jx = JaxEngine(cfg, traces, max_cycles=50_000)
        jx.run()
        assert _dicts(spec.final_dumps()) == _dicts(jx.final_dumps())
        assert _nonzero(spec.stats()) == \
            _nonzero(engine_stats(jx.state))
        for k, v in engine_stats(jx.state).items():
            totals[k] = totals.get(k, 0) + int(v)
    if counter:  # the variant's escape hatch must actually trigger
        assert totals.get(counter, 0) > 0, totals


# -- checkpoint round-trips carrying the owner plane -----------------------


def test_jax_checkpoint_roundtrip_owner_plane(tmp_path):
    from hpa2_tpu.ops.engine import build_batched_run_chunk
    from hpa2_tpu.ops.state import SimState, init_state_batched
    from hpa2_tpu.utils.checkpoint import load_state, save_state

    cfg = SystemConfig(num_procs=4, semantics=ROBUST, protocol="moesi")
    st = init_state_batched(
        cfg, *gen_uniform_random_arrays(cfg, 3, 24, seed=0)
    )
    # advance until a line is actually OWNED so the checkpoint carries
    # a live pointer, not the all- -1 initial plane
    chunk = build_batched_run_chunk(cfg, 8)
    for _ in range(64):
        st = chunk(st)
        if np.any(np.asarray(st.dir_owner) >= 0):
            break
    assert np.any(np.asarray(st.dir_owner) >= 0), (
        "workload never entered SO; the round-trip would not cover "
        "a live owner plane"
    )
    path = str(tmp_path / "moesi.npz")
    save_state(path, st, cfg)
    loaded, config = load_state(path)
    assert config == cfg
    for name, la, lb in zip(SimState._fields, st, loaded):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name


def test_spec_checkpoint_roundtrip_owner(tmp_path):
    import os

    from hpa2_tpu.utils.checkpoint import (
        load_spec_state,
        save_spec_state,
    )
    from hpa2_tpu.utils.trace import gen_uniform_random

    cfg = SystemConfig(num_procs=4, semantics=ROBUST, protocol="moesi")
    traces = gen_uniform_random(cfg, 24, seed=5)

    straight = SpecEngine(cfg, traces)
    straight.run()

    eng = SpecEngine(cfg, traces)
    steps = 0
    # step to the first cycle boundary where a line is OWNED, so the
    # JSON round-trip actually carries a live owner pointer
    while not any(e.owner >= 0
                  for n in eng.nodes for e in n.directory):
        eng.step()
        steps += 1
        assert steps < 5_000, "workload never entered SO"
    owners = [e.owner for n in eng.nodes for e in n.directory]
    assert any(o >= 0 for o in owners)
    path = os.path.join(tmp_path, "moesi_ckpt.json")
    save_spec_state(path, eng)
    del eng

    resumed = load_spec_state(path)
    assert [e.owner for n in resumed.nodes
            for e in n.directory] == owners
    resumed.run()
    assert _dicts(resumed.final_dumps()) == \
        _dicts(straight.final_dumps())
    assert resumed.counters == straight.counters


# -- loud configuration errors ---------------------------------------------


def test_unknown_protocol_raises():
    with pytest.raises(ValueError, match="unknown protocol"):
        SystemConfig(protocol="mosi")


@pytest.mark.parametrize("fmt", ["limited", "limited:0", "coarse:x",
                                 "sparse", "coarse:"])
def test_bad_directory_format_raises(fmt):
    with pytest.raises(ValueError):
        SystemConfig(directory_format=fmt)


def test_sharded_step_requires_mesi_full():
    from hpa2_tpu.ops.step import build_step

    cfg = SystemConfig(num_procs=8, semantics=ROBUST, protocol="moesi")
    with pytest.raises(ValueError, match="MESI/full-bitvector"):
        build_step(cfg, axis_name="nodes", shards=2)


def test_pallas_engine_rejects_protocol_variants():
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    cfg = SystemConfig(num_procs=4, semantics=ROBUST, protocol="mesif")
    op, addr, val, length = gen_uniform_random_arrays(cfg, 1, 4, seed=0)
    with pytest.raises(ValueError, match="specialized to the MESI"):
        PallasEngine(cfg, op, addr, val, length, block=1,
                     interpret=True)


def test_cli_gates_protocol_variants():
    from hpa2_tpu.cli import main

    base = ["bench", "--nodes", "4", "--batch", "1", "--instrs", "4"]
    with pytest.raises(SystemExit):
        main(base + ["--backend", "pallas", "--protocol", "moesi"])
    with pytest.raises(SystemExit):
        main(base + ["--backend", "omp",
                     "--directory-format", "coarse:4"])


# -- multi-message probe gate (analysis/extract.py satellite) --------------


@pytest.mark.parametrize("protocol", ["mesi", "moesi", "mesif"])
def test_multi_message_probes_agree(protocol):
    from hpa2_tpu.analysis.extract import diff_multi_backend

    assert diff_multi_backend(ROBUST, protocol) == []
