"""Tier-1 coverage for the compiled-program contract engine
(hpa2_tpu/analysis/contracts.py + analysis/ir.py).

Three planes:

* the checked-in registry still carries the exact historical pins the
  old ad-hoc test walkers enforced (no guard weakened by the
  migration),
* the pin files on disk are present and digest-fresh for every
  contract with pinned rules,
* a seeded op-module mutation makes the check FAIL with a structural
  drift diff that names the offending primitive — the negative test
  that proves the engine can actually catch a regression.

Everything here runs on plain CPU (num_procs=4 programs, no mesh);
the device-hungry contract points are exercised by `analysis
contracts --check` under the virtual mesh in run_static.sh.
"""

import pytest

from hpa2_tpu.analysis import contracts
from hpa2_tpu.analysis.contracts import (
    check_contract,
    load_pins,
    registry,
    seeded_mutation,
    spec_digest,
)


def _by_name(name):
    c = next((c for c in registry() if c.name == name), None)
    assert c is not None, f"contract {name!r} missing from registry"
    return c


def _rules(c):
    return {r.key: (r.op, r.expect) for r in c.rules}


# -- registry shape ---------------------------------------------------


def test_registry_covers_required_engine_paths():
    cs = registry()
    assert len(cs) >= 8
    names = {c.name for c in cs}
    # one serving-session and one recovery-resume program, per the
    # coverage floor
    assert "pallas-serving-session" in names
    assert "serving-recovery-resume" in names
    assert {c.engine for c in cs} >= {"xla", "pallas", "serving",
                                      "sharded"}
    assert len(names) == len(cs), "duplicate contract names"


# -- the migrated historical pins, verbatim ---------------------------


def test_run_loop_contract_carries_elision_pins():
    rules = _rules(_by_name("xla-run-loop"))
    assert rules["elided.reduce_min"] == ("==", 1)
    assert rules["elided.cond"] == ("==", 1)
    for k in ("elided.while", "elided.scan", "elided.dot_general",
              "elided.sort"):
        assert rules[k] == ("==", 0), k
    assert rules["lockstep.cond"] == ("==", 0)
    assert rules["lockstep.extra_eqns"] == (">=", 1)


def test_cycle_body_contract_carries_op_ceilings():
    rules = _rules(_by_name("pallas-cycle-body"))
    assert rules["eqns.plain"] == ("<=", 2172)
    assert rules["eqns.snap"] == ("<=", 2194)
    assert rules["collectives"] == ("==", 0)


def test_node_sharded_contracts_carry_collective_pins():
    a2a = _rules(_by_name("node-sharded-pallas-a2a"))
    assert a2a["ppermute"] == ("==", 0)
    assert a2a["all_to_all"] == ("==", 2)
    assert a2a["psum"] == ("==", 2)
    assert a2a["pmax"] == ("==", 3)
    assert a2a["gather"] == ("==", 0)
    jx = _rules(_by_name("node-sharded-jax-a2a"))
    assert jx["all_to_all"] == ("==", 2)
    assert jx["pmax"] == ("==", 1)
    assert jx["gather"] == ("==", 0)
    pw = _rules(_by_name("node-sharded-jax-pairwise"))
    assert pw["ppermute"] == ("==", 6)
    assert pw["all_to_all"] == ("==", 0)


def test_dma_and_gather_guards_present():
    dma = _rules(_by_name("pallas-stream-dma"))
    assert dma["dma.in_while"] == ("==", 0)
    assert dma["dma_start.total"] == (">=", 2)
    assert _rules(_by_name("xla-run-interconnect"))["gather"] == \
        ("==", 0)
    assert _rules(_by_name("data-sharded-pallas"))[
        "shard_body.collectives"] == ("==", 0)


# -- pin files --------------------------------------------------------


def test_pin_files_present_and_digest_fresh():
    for c in registry():
        pinned = [r.key for r in c.rules if r.expect is None]
        if not pinned:
            continue
        doc = load_pins(c)
        assert doc is not None, (
            f"{c.name}: pinned rules but no pin file — run "
            "`analysis contracts --repin`"
        )
        assert doc.get("digest") == spec_digest(c), (
            f"{c.name}: rule spec changed since the pin file was "
            "minted — run `analysis contracts --repin`"
        )
        missing = [k for k in pinned if k not in doc.get("pins", {})]
        assert not missing, f"{c.name}: unpinned keys {missing}"


# -- live measurement reproduces the pins (CPU-safe point) ------------


def test_run_loop_measurement_is_drift_free():
    c = _by_name("xla-run-loop")
    drifts = check_contract(c, c.measure())
    assert not drifts, "\n".join(d.render() for d in drifts)


# -- seeded-mutation negative test ------------------------------------


def test_seeded_mutation_fails_with_named_drift_diff():
    """Perturb ops/step.py (force the lockstep escape hatch on) and
    the xla-run-loop contract must fail, with a drift diff that names
    the structural change — the reduce_min/cond shape of the elided
    body."""
    c = _by_name("xla-run-loop")
    with seeded_mutation(1):
        drifts = check_contract(c, c.measure())
    assert drifts, "mutation went undetected — the contract is vacuous"
    keys = {d.key for d in drifts}
    assert keys & {"elided.reduce_min", "elided.cond"}, keys
    diff = "\n".join(d.render() for d in drifts)
    assert "expected" in diff and "found" in diff
    # ...and the mutation context restores the real engine afterwards
    assert not check_contract(c, c.measure())


def test_seeded_mutation_even_seed_rewires_exchange_plan():
    from hpa2_tpu.ops import exchange

    with seeded_mutation(0):
        plan = exchange.make_plan(4, "a2a", 0)
        assert plan.mode == "pairwise"
    assert exchange.make_plan(4, "a2a", 0).mode == "a2a"


# -- counter-backfill lint rule (cross-file, negative + clean) --------


def _write_stats_pair(root, backfill_names):
    ops = root / "hpa2_tpu" / "ops"
    utils = root / "hpa2_tpu" / "utils"
    ops.mkdir(parents=True)
    utils.mkdir(parents=True)
    (ops / "engine.py").write_text(
        "def engine_stats(st):\n"
        "    core = {\"cycle\": st.cycle}\n"
        "    out = dict(core)\n"
        "    if st.n_shiny:\n"
        "        out[\"n_shiny\"] = int(st.n_shiny)\n"
        "    return out\n"
    )
    names = ", ".join(f"\"{n}\"" for n in backfill_names)
    (utils / "checkpoint.py").write_text(
        f"_ZERO_BACKFILL = frozenset({{{names}}})\n"
    )


def test_counter_backfill_lint_flags_unbackfilled_counter(tmp_path):
    from hpa2_tpu.analysis.lint import lint_counter_backfill

    _write_stats_pair(tmp_path, ["n_other"])
    findings = lint_counter_backfill(str(tmp_path))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "counter-backfill"
    assert "n_shiny" in f.message
    assert "_ZERO_BACKFILL" in f.message


def test_counter_backfill_lint_clean_when_backfilled(tmp_path):
    from hpa2_tpu.analysis.lint import lint_counter_backfill

    _write_stats_pair(tmp_path, ["n_shiny"])
    assert lint_counter_backfill(str(tmp_path)) == []


def test_counter_backfill_skips_roots_without_engine(tmp_path):
    # synthetic lint-test roots carry only the files they probe
    from hpa2_tpu.analysis.lint import lint_counter_backfill

    assert lint_counter_backfill(str(tmp_path)) == []


# -- drift rendering --------------------------------------------------


def test_drift_render_carries_location_and_why():
    d = contracts.Drift("c", "gather", "==", 0, 2,
                        where="eqns[3]:while > eqns[7]:all_gather",
                        why="gather-the-world ban")
    out = d.render()
    assert "gather: expected == 0, found 2" in out
    assert "eqns[7]:all_gather" in out
    assert "gather-the-world ban" in out
