"""Counters + invariant checking (SURVEY.md §7.2 item 7).

The reference has zero observability (SURVEY.md §5); the rebuild's
counters are defined by the spec engine and the JAX backend must agree
exactly — another differential surface on top of state parity.
"""

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.utils.invariants import check_invariants
from hpa2_tpu.utils.trace import gen_producer_consumer, gen_uniform_random

ROBUST = SystemConfig(semantics=Semantics().robust())


@pytest.mark.parametrize("seed", [0, 3])
def test_jax_counters_match_spec(seed):
    traces = gen_uniform_random(ROBUST, 40, seed=seed)
    spec = SpecEngine(ROBUST, traces)
    spec.run()
    jx = JaxEngine(ROBUST, traces).run()
    js = jx.stats()
    for key in set(js) | set(spec.counters):
        assert spec.counters.get(key, 0) == js.get(key, 0), (
            f"{key}: spec={spec.counters.get(key, 0)} jax={js.get(key, 0)}"
        )
    # hit/miss accounting is complete
    assert (
        js["read_hits"] + js["read_misses"]
        + js["write_hits"] + js["write_misses"]
        == js["instructions"]
    )


def test_counters_intelligible_producer_consumer():
    cfg = SystemConfig(num_procs=8, semantics=Semantics().robust())
    traces = gen_producer_consumer(cfg, 32, seed=1)
    eng = JaxEngine(cfg, traces).run()
    s = eng.stats()
    assert s["instructions"] == 8 * 32
    assert s["msgs_total"] == sum(
        v for k, v in s.items() if k.startswith("msg_")
    )
    # cross-node reads must have triggered read misses and requests
    assert s["read_misses"] > 0 and s["msg_READ_REQUEST"] > 0


@pytest.mark.parametrize("gen,seed", [
    (gen_uniform_random, 0),
    (gen_uniform_random, 7),
    (gen_producer_consumer, 2),
])
def test_invariants_hold_at_quiescence(gen, seed):
    cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
    traces = gen(cfg, 48, seed=seed)
    for eng in (SpecEngine(cfg, traces), JaxEngine(cfg, traces)):
        eng.run()
        assert check_invariants(eng.final_dumps(), cfg) == []


def test_invariants_catch_corruption():
    cfg = SystemConfig(semantics=Semantics().robust())
    traces = gen_uniform_random(cfg, 24, seed=5)
    eng = SpecEngine(cfg, traces)
    eng.run()
    dumps = eng.final_dumps()
    # fabricate a second writer for an address someone holds M/E
    victim = next(
        (d, i)
        for d in dumps
        for i in range(cfg.cache_size)
        if d.cache_state[i] in (0, 1) and d.cache_addr[i] >= 0
    )
    d, i = victim
    other = dumps[(d.proc_id + 1) % cfg.num_procs]
    other.cache_addr[i] = d.cache_addr[i]
    other.cache_state[i] = 0  # MODIFIED
    assert any(
        "single-writer" in msg for msg in check_invariants(dumps, cfg)
    )


class TestMsgTrace:
    """DEBUG_MSG-analog per-message logging (assignment.c:170-174
    receive, 734-738 send) on the spec and native engines, checked
    against the hand-derived traffic of one WRITE_REQUEST miss flow."""

    # node 0 writes block 0 of node 1's memory (addr 0x10): issue ->
    # WRITE_REQUEST to home 1 (dir U) -> REPLY_WR -> fill.  Exactly
    # two messages; sends log at enqueue, receives at dequeue.
    EXPECTED = [
        "Processor 0 sent msg to: 1, type: 1, address: 0x10",
        "Processor 1 msg from: 0, type: 1, address: 0x10",
        "Processor 1 sent msg to: 0, type: 3, address: 0x10",
        "Processor 0 msg from: 1, type: 3, address: 0x10",
    ]

    @staticmethod
    def _traces(config):
        from hpa2_tpu.models.protocol import Instr

        return [[Instr("W", config.make_addr(1, 0), 42)], []]

    def test_spec_engine_msg_log(self):
        from hpa2_tpu.config import SystemConfig
        from hpa2_tpu.models.spec_engine import SpecEngine

        cfg = SystemConfig(num_procs=2)
        eng = SpecEngine(cfg, self._traces(cfg), trace_msgs=True)
        eng.run()
        assert eng.msg_log == self.EXPECTED

    def test_native_lockstep_msg_log(self, tmp_path):
        import os

        from hpa2_tpu import native
        from hpa2_tpu.config import SystemConfig
        from tests.test_native import write_traces

        cfg = SystemConfig(num_procs=2)
        tr_dir = str(tmp_path / "tr")
        write_traces(self._traces(cfg), tr_dir)
        out = str(tmp_path / "out")
        os.makedirs(out)
        log_path = str(tmp_path / "msgs.log")
        native.run_trace_dir(
            cfg, tr_dir, out, mode="lockstep",
            msg_trace_path=log_path,
        )
        got = open(log_path).read().strip().splitlines()
        assert got == self.EXPECTED

    def test_native_omp_msg_log_complete(self, tmp_path):
        """Free-running: interleaving is nondeterministic, but the log
        must contain exactly one send and one receive per message."""
        import os

        from hpa2_tpu import native
        from hpa2_tpu.config import SystemConfig
        from tests.test_native import write_traces

        cfg = SystemConfig(num_procs=2)
        tr_dir = str(tmp_path / "tr")
        write_traces(self._traces(cfg), tr_dir)
        out = str(tmp_path / "out")
        os.makedirs(out)
        log_path = str(tmp_path / "msgs.log")
        res = native.run_trace_dir(
            cfg, tr_dir, out, mode="omp", msg_trace_path=log_path,
        )
        lines = open(log_path).read().strip().splitlines()
        sends = [l for l in lines if " sent msg to: " in l]
        recvs = [l for l in lines if " msg from: " in l]
        assert len(sends) == res.messages
        assert sorted(sends) == sorted(self.EXPECTED[0::2])
        assert sorted(recvs) == sorted(self.EXPECTED[1::2])


@pytest.mark.parametrize("k", [2, 3])
def test_messages_per_cycle_schedule(k):
    """The k-messages-per-cycle lockstep schedule (PERF.md lever 4,
    SystemConfig.messages_per_cycle) on the spec engine: still
    quiesces, executes the full workload, keeps protocol invariants,
    and strictly shortens the cycle count vs k=1 on a queue-bound
    workload."""
    import dataclasses

    base = SystemConfig(
        num_procs=8, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    cfg_k = dataclasses.replace(base, messages_per_cycle=k)
    traces = gen_uniform_random(base, 60, seed=11)

    ref = SpecEngine(base, traces)
    ref.run(max_cycles=50_000)
    eng = SpecEngine(cfg_k, traces)
    eng.run(max_cycles=50_000)

    assert eng.instructions == ref.instructions == 8 * 60
    assert check_invariants(eng.final_dumps(), cfg_k) == []
    assert eng.cycle < ref.cycle


def test_messages_per_cycle_unsupported_engines_guard():
    """Engines that implement only the reference-shaped k=1 schedule
    must refuse a k>1 config instead of silently diverging from the
    spec engine's schedule."""
    import dataclasses

    from hpa2_tpu import native
    from hpa2_tpu.ops.step import build_step
    from hpa2_tpu.ops.pallas_engine import build_cycle

    cfg = dataclasses.replace(
        SystemConfig(semantics=Semantics().robust()),
        messages_per_cycle=2,
    )
    with pytest.raises(ValueError, match="messages_per_cycle"):
        build_step(cfg)
    with pytest.raises(ValueError, match="messages_per_cycle"):
        build_cycle(cfg, bb=1)
    with pytest.raises(native.NativeError, match="messages_per_cycle"):
        native._check_config(cfg)
