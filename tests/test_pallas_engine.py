"""Differential gates for the VMEM-resident Pallas engine
(ops/pallas_engine.py) against the XLA engine — which is itself gated
against the Python spec engine — on random workloads.

Runs in Pallas interpreter mode (CPU); the kernel path is exercised on
real TPU by bench.py.
"""

import numpy as np
import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.protocol import Instr
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.utils.trace import gen_uniform_random_arrays


def _traces_from_arrays(op, addr, val, b, n_procs):
    return [
        [
            Instr("W", int(a), int(v)) if o == 1 else Instr("R", int(a))
            for o, a, v in zip(op[b, n], addr[b, n], val[b, n])
        ]
        for n in range(n_procs)
    ]


def _dicts(dumps):
    return [d.__dict__ for d in dumps]


@pytest.mark.parametrize(
    "n_procs,batch,block,t,seed",
    [
        (4, 4, 4, 24, 0),
        (8, 6, 3, 20, 1),   # batch split over 2 grid blocks
        (4, 2, 2, 40, 2),
    ],
)
def test_pallas_matches_xla_engine(n_procs, batch, block, t, seed):
    cfg = SystemConfig(
        num_procs=n_procs, msg_buffer_size=64,
        semantics=Semantics().robust(),
    )
    op, addr, val, length = gen_uniform_random_arrays(cfg, batch, t, seed=seed)
    pe = PallasEngine(
        cfg, op, addr, val, length, block=block, cycles_per_call=64,
        interpret=True,
    ).run()
    total_spec = {}
    for b in range(batch):
        jx = JaxEngine(
            cfg, _traces_from_arrays(op, addr, val, b, n_procs)
        ).run()
        assert _dicts(jx.final_dumps()) == _dicts(pe.system_final_dumps(b))
        assert _dicts(jx.snapshots()) == _dicts(pe.system_snapshots(b))
        for k, v in jx.stats().items():
            total_spec[k] = total_spec.get(k, 0) + v
    ps = pe.stats()
    for k in set(ps) | set(total_spec):
        assert total_spec.get(k, 0) == ps.get(k, 0), (
            f"{k}: xla={total_spec.get(k, 0)} pallas={ps.get(k, 0)}"
        )


def test_pallas_parity_semantics_default_drop():
    """Local-only traffic runs clean under the parity (drop) policy."""
    cfg = SystemConfig(num_procs=4, msg_buffer_size=32)
    from hpa2_tpu.utils.trace import gen_local_only

    traces = gen_local_only(cfg, 24, seed=3)
    op = np.full((1, 4, 24), -1, np.int32)
    addr = np.zeros((1, 4, 24), np.int32)
    val = np.zeros((1, 4, 24), np.int32)
    length = np.zeros((1, 4), np.int32)
    for n, tr in enumerate(traces):
        length[0, n] = len(tr)
        for j, ins in enumerate(tr):
            op[0, n, j] = 0 if ins.op == "R" else 1
            addr[0, n, j] = ins.address
            val[0, n, j] = ins.value
    pe = PallasEngine(
        cfg, op, addr, val, length, block=1, cycles_per_call=64,
        interpret=True,
    ).run()
    jx = JaxEngine(cfg, traces).run()
    assert _dicts(jx.final_dumps()) == _dicts(pe.system_final_dumps(0))


def test_pallas_tiny_capacity_backpressures():
    """With msg_buffer_size=4 the old engines aborted on overflow; the
    deferred-send backpressure now completes the run with bounded
    queues (SURVEY.md §5 masked/deferred-send requirement)."""
    cfg = SystemConfig(
        num_procs=8, msg_buffer_size=4, semantics=Semantics().robust()
    )
    op, addr, val, length = gen_uniform_random_arrays(cfg, 2, 64, seed=0)
    pe = PallasEngine(
        cfg, op, addr, val, length, block=2, cycles_per_call=32,
        interpret=True,
    ).run(max_cycles=100_000)
    assert pe.instructions == 2 * 8 * 64


def test_pallas_trace_window_matches_spec_segmented():
    """The bench configuration — trace_window segmentation, gate=False,
    snapshots=False — against the spec engine run on the same window
    schedule (SpecEngine.continue_with).  Gates the exact path the
    perf numbers are measured on."""
    from hpa2_tpu.models.spec_engine import SpecEngine

    cfg = SystemConfig(
        num_procs=8, msg_buffer_size=16, semantics=Semantics().robust()
    )
    batch, t, w = 4, 40, 16
    op, addr, val, length = gen_uniform_random_arrays(cfg, batch, t, seed=9)
    pe = PallasEngine(
        cfg, op, addr, val, length, block=2, cycles_per_call=32,
        interpret=True, snapshots=False, gate=False, trace_window=w,
    ).run()

    total_instr = 0
    for b in range(batch):
        traces = _traces_from_arrays(op, addr, val, b, 8)
        spec = SpecEngine(cfg, [tr[:w] for tr in traces])
        spec.run()
        for s in range(w, t, w):
            spec.continue_with([tr[s:s + w] for tr in traces])
            spec.run()
        assert _dicts(spec.final_dumps()) == _dicts(
            pe.system_final_dumps(b)
        )
        total_instr += spec.instructions
    assert total_instr == pe.instructions


# -- windowed-trace edge cases on the HBM-streaming run program -------


def _spec_on_window_schedule(cfg, op, addr, val, b, n, w, t):
    """Spec engine run on the same legal window schedule the engine
    executes (w instructions per core per segment, quiesce between)."""
    from hpa2_tpu.models.spec_engine import SpecEngine

    traces = _traces_from_arrays(op, addr, val, b, n)
    spec = SpecEngine(cfg, [tr[:w] for tr in traces])
    spec.run()
    for s in range(w, t, w):
        spec.continue_with([tr[s:s + w] for tr in traces])
        spec.run()
    return spec


@pytest.mark.parametrize(
    "w,t,gate",
    [
        (7, 20, False),   # window does not divide the trace length
        (1, 6, True),     # degenerate one-instruction windows
        (20, 20, False),  # single window spanning the whole trace
        (8, 40, True),    # many exact windows, in-kernel gate on
    ],
)
def test_stream_windowed_edges_bitexact(w, t, gate):
    """The streaming program (double-buffered HBM prefetch, segment
    loop in-kernel) vs the legacy host-composed window loop: every
    carried plane must match bit-for-bit on the ragged window shapes,
    and both must match the spec engine on the same window schedule.
    A prefetch off-by-one (wrong segment consumed, tail window length
    mis-clipped) shows up here as a plane diff naming the field."""
    cfg = SystemConfig(
        num_procs=8, msg_buffer_size=16, semantics=Semantics().robust()
    )
    batch = 4
    op, addr, val, length = gen_uniform_random_arrays(
        cfg, batch, t, seed=20 + w)

    def build(stream):
        return PallasEngine(cfg, op, addr, val, length, block=2,
                            cycles_per_call=32, interpret=True,
                            snapshots=False, trace_window=w,
                            gate=gate, stream=stream)

    se = build(True).run(max_cycles=400_000)
    le = build(False).run(max_cycles=400_000)
    for f in se.state:
        assert (
            np.asarray(se.state[f]) == np.asarray(le.state[f])
        ).all(), f"stream/legacy diverged on plane {f!r}"
    for b in range(batch):
        spec = _spec_on_window_schedule(cfg, op, addr, val, b, 8, w, t)
        assert _dicts(spec.final_dumps()) == _dicts(
            se.system_final_dumps(b)
        ), f"b={b}"


def test_stream_windowed_split_plane_22_nodes():
    """22 nodes (> 21) engages the split sharer planes on the
    streaming path, with a window (5 over t=12) that leaves a ragged
    tail segment — the split dirs{w} planes and the trace scratch ride
    separate DMA channels, so this pins their interaction."""
    cfg = SystemConfig(num_procs=22, cache_size=2, mem_size=4,
                       msg_buffer_size=16,
                       semantics=Semantics().robust())
    op, addr, val, length = gen_uniform_random_arrays(cfg, 2, 12, seed=4)

    def build(stream):
        return PallasEngine(cfg, op, addr, val, length, block=2,
                            cycles_per_call=32, interpret=True,
                            snapshots=False, trace_window=5,
                            gate=False, stream=stream)

    se = build(True).run(max_cycles=400_000)
    le = build(False).run(max_cycles=400_000)
    for f in se.state:
        assert (
            np.asarray(se.state[f]) == np.asarray(le.state[f])
        ).all(), f"stream/legacy diverged on plane {f!r}"
    for b in range(2):
        spec = _spec_on_window_schedule(cfg, op, addr, val, b, 22, 5, 12)
        assert _dicts(spec.final_dumps()) == _dicts(
            se.system_final_dumps(b)
        ), f"b={b}"


def test_windowed_snapshots_rejected():
    """Dump-at-local-completion is defined on the whole trace; a
    multi-segment window schedule must be refused up front, not
    produce wrong snapshots later."""
    cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
    op, addr, val, length = gen_uniform_random_arrays(cfg, 1, 8, seed=0)
    with pytest.raises(ValueError, match="snapshots=False"):
        PallasEngine(cfg, op, addr, val, length, block=1,
                     interpret=True, snapshots=True, trace_window=4)


def test_single_window_snapshots_allowed():
    """trace_window == t is one segment, so snapshots stay legal —
    and on the streaming path the snapshot planes round-trip through
    the DMA-staged scratch; they must still match the XLA engine."""
    cfg = SystemConfig(num_procs=4, msg_buffer_size=32,
                       semantics=Semantics().robust())
    op, addr, val, length = gen_uniform_random_arrays(cfg, 2, 10, seed=6)
    pe = PallasEngine(cfg, op, addr, val, length, block=2,
                      cycles_per_call=32, interpret=True,
                      snapshots=True, trace_window=10, stream=True).run()
    for b in range(2):
        jx = JaxEngine(cfg, _traces_from_arrays(op, addr, val, b, 4)).run()
        assert _dicts(jx.snapshots()) == _dicts(pe.system_snapshots(b))
        assert _dicts(jx.final_dumps()) == _dicts(pe.system_final_dumps(b))


def test_pallas_run_idempotent_and_not_resumable():
    cfg = SystemConfig(
        num_procs=4, msg_buffer_size=16, semantics=Semantics().robust()
    )
    op, addr, val, length = gen_uniform_random_arrays(cfg, 2, 8, seed=1)
    pe = PallasEngine(cfg, op, addr, val, length, block=2,
                      cycles_per_call=32, interpret=True).run()
    before = pe.instructions
    pe.run()  # no-op: counters must not double
    assert pe.instructions == before


@pytest.mark.slow  # ~5 min in interpret mode (scripts/run_slow.sh)
def test_split_plane_64_nodes_sw3():
    """Three sharer words (SW=3) on the split-plane path: 64 nodes, a
    geometry the native backend also caps at (single-word 64-bit mask)
    and the reference's 1-byte bitVector cannot express at all.  The
    33-node sweep row covers SW=2 every run; this pins the >2-word
    generality of the sv_* helpers on demand."""
    from hpa2_tpu.models.spec_engine import SpecEngine

    cfg = SystemConfig(num_procs=64, cache_size=2, mem_size=4,
                       msg_buffer_size=16,
                       semantics=Semantics().robust())
    op, addr, val, length = gen_uniform_random_arrays(cfg, 2, 6, seed=9)
    pe = PallasEngine(cfg, op, addr, val, length, block=2,
                      cycles_per_call=32, interpret=True)
    pe.run(max_cycles=100_000)
    for b in range(2):
        spec = SpecEngine(
            cfg, _traces_from_arrays(op, addr, val, b, 64)
        )
        spec.run(max_cycles=50_000)
        assert _dicts(pe.system_final_dumps(b)) == _dicts(
            spec.final_dumps()
        ), f"b={b}"


@pytest.mark.parametrize("suite", ["sample", "test_1", "test_2"])
def test_pallas_deterministic_fixture_parity(reference_tests_dir, suite):
    """The fourth backend runs the reference corpus too: byte-exact
    dump-at-local-completion parity on the deterministic suites (the
    CLI exposes this as `run --backend pallas`)."""
    from hpa2_tpu.utils.dump import format_processor_state
    from hpa2_tpu.utils.trace import load_trace_dir, traces_to_arrays

    cfg = SystemConfig()
    traces = load_trace_dir(str(reference_tests_dir / suite), cfg)
    eng = PallasEngine(cfg, *traces_to_arrays(cfg, [traces]))
    eng.run(100_000)
    for nd in eng.system_snapshots(0):
        want = (
            reference_tests_dir / suite / f"core_{nd.proc_id}_output.txt"
        ).read_text()
        assert format_processor_state(nd, cfg) == want, (
            f"{suite} core_{nd.proc_id}"
        )


# -- block auto-shrink ------------------------------------------------


class TestChooseBlock:
    """The engine needs block | b for an even grid.  The old shrink
    loop walked down silently — a prime batch of 509 quietly ran at
    block=1 (509 sequential grid steps, no lane parallelism).  The
    divisor is still chosen automatically, but a severe shrink (< half
    the request) now warns."""

    def test_prime_batch_warns_and_degrades_to_1(self):
        from hpa2_tpu.ops.pallas_engine import choose_block

        with pytest.warns(RuntimeWarning, match="block divisor"):
            assert choose_block(509, 256) == 1

    def test_exact_divisor_is_silent(self):
        import warnings

        from hpa2_tpu.ops.pallas_engine import choose_block

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert choose_block(509, 509) == 509
            assert choose_block(512, 256) == 256
            assert choose_block(1024, 4096) == 1024  # capped at b

    def test_mild_shrink_is_silent(self):
        import warnings

        from hpa2_tpu.ops.pallas_engine import choose_block

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            # 6 is the largest divisor of 12 <= 8: a mild (>= half)
            # shrink, not worth a warning
            assert choose_block(12, 8) == 6

    def test_engine_surfaces_the_warning(self):
        # the b=509 regression, end to end through __init__
        cfg = SystemConfig(num_procs=4,
                           semantics=Semantics().robust())
        arrays = gen_uniform_random_arrays(cfg, 509, 4, seed=0)
        with pytest.warns(RuntimeWarning, match="lane parallelism"):
            eng = PallasEngine(cfg, *arrays, block=256)
        assert eng.block == 1
        assert eng.b % eng.block == 0
