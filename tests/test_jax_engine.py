"""JAX backend tests: differential vs the Python spec oracle, fixture
parity, batching, and wide (multi-word sharer mask) geometries.

Both engines implement the same deterministic lockstep semantics, so
their trajectories must agree exactly — canonical snapshots, final
quiescent state, cycle/instruction counts (SURVEY.md §7.2 gate 3).
"""

import dataclasses
import os

import pytest

from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine, StallError
from hpa2_tpu.ops.engine import BatchJaxEngine, JaxEngine
from hpa2_tpu.utils.dump import format_processor_state
from hpa2_tpu.utils.parity import discover_run_sets
from hpa2_tpu.utils.trace import (
    gen_producer_consumer,
    gen_uniform_random,
    load_instruction_order,
    load_trace_dir,
)

CONFIG = SystemConfig()


def dumps_equal(a, b):
    return [dataclasses.asdict(x) for x in a] == [
        dataclasses.asdict(y) for y in b
    ]


def assert_engines_agree(spec: SpecEngine, jx: JaxEngine):
    assert dumps_equal(spec.snapshots(), jx.snapshots())
    assert dumps_equal(spec.final_dumps(), jx.final_dumps())
    assert spec.cycle == jx.cycle
    assert spec.counters["instructions"] == jx.instructions


@pytest.mark.parametrize(
    "suite", ["sample", "test_1", "test_2", "test_3", "test_4"]
)
def test_free_run_differential(reference_tests_dir, suite):
    traces = load_trace_dir(str(reference_tests_dir / suite), CONFIG)
    spec = SpecEngine(CONFIG, traces)
    spec.run()
    jx = JaxEngine(CONFIG, traces).run()
    assert_engines_agree(spec, jx)


@pytest.mark.parametrize("suite", ["test_3", "test_4"])
def test_replay_differential(reference_tests_dir, suite):
    suite_dir = str(reference_tests_dir / suite)
    traces = load_trace_dir(suite_dir, CONFIG)
    for run_dir in discover_run_sets(suite_dir):
        order = load_instruction_order(
            os.path.join(run_dir, "instruction_order.txt")
        )
        spec = SpecEngine(CONFIG, traces, replay_order=order)
        spec.run()
        jx = JaxEngine(CONFIG, traces, replay_order=order).run()
        assert_engines_agree(spec, jx)


def test_jax_fixture_parity_direct(reference_tests_dir):
    """The JAX engine reproduces fixtures byte-exactly on its own:
    deterministic suites via the canonical snapshot, a nondeterministic
    run set via captured dump-timing candidates."""
    for suite in ["sample", "test_1", "test_2"]:
        suite_dir = str(reference_tests_dir / suite)
        traces = load_trace_dir(suite_dir, CONFIG)
        order = load_instruction_order(
            os.path.join(suite_dir, "instruction_order.txt")
        )
        jx = JaxEngine(CONFIG, traces, replay_order=order).run()
        for dump in jx.snapshots():
            with open(
                os.path.join(suite_dir, f"core_{dump.proc_id}_output.txt")
            ) as fh:
                assert format_processor_state(dump, CONFIG) == fh.read()

    # nondeterministic suite through the shared parity harness with the
    # JAX engine plugged in as engine_cls
    from hpa2_tpu.utils.parity import check_suite

    results = check_suite(
        str(reference_tests_dir / "test_3"), CONFIG, engine_cls=JaxEngine
    )
    for run_dir, diffs in results.items():
        assert not diffs, f"{run_dir}:\n" + "\n".join(diffs.values())


@pytest.mark.parametrize("seed", [0, 1])
def test_random_differential_8_nodes(seed):
    cfg = SystemConfig(
        num_procs=8, max_instr_num=0, semantics=Semantics().robust()
    )
    traces = gen_uniform_random(cfg, 60, seed=seed)
    spec = SpecEngine(cfg, traces)
    spec.run()
    jx = JaxEngine(cfg, traces).run()
    assert_engines_agree(spec, jx)


def test_wide_sharer_mask_differential():
    """40 nodes -> 2 uint32 sharer words: exercises the multi-word
    bitmask path the reference structurally cannot reach (1-byte
    bitVector, assignment.c:49)."""
    cfg = SystemConfig(
        num_procs=40, max_instr_num=0, semantics=Semantics().robust()
    )
    traces = gen_producer_consumer(cfg, 12, seed=3)
    spec = SpecEngine(cfg, traces)
    spec.run()
    jx = JaxEngine(cfg, traces).run()
    assert dumps_equal(spec.final_dumps(), jx.final_dumps())
    assert dumps_equal(spec.snapshots(), jx.snapshots())


def test_batched_ensemble_matches_singles():
    cfg = SystemConfig(max_instr_num=0, semantics=Semantics().robust())
    batch = [
        gen_uniform_random(cfg, 20, seed=s) for s in (0, 1, 2, 0)
    ]
    be = BatchJaxEngine(cfg, batch).run()
    for b, traces in enumerate(batch):
        single = JaxEngine(cfg, traces).run()
        assert dumps_equal(be.system_snapshots(b), single.snapshots())
    # identical seeds -> identical results inside one batch
    assert dumps_equal(be.system_snapshots(0), be.system_snapshots(3))


def test_livelock_detected_not_hung():
    """drop-policy livelock surfaces as StallError (the reference spins
    forever; SURVEY.md §6.3)."""
    from hpa2_tpu.models.protocol import Instr

    traces = [
        [Instr("R", 0x10), Instr("R", 0x00)],
        [Instr("R", 0x10)],
        [],
        [],
    ]
    with pytest.raises(StallError):
        JaxEngine(CONFIG, traces, max_cycles=3000).run()
