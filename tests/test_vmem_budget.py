"""Tier-1 guards derived from the static VMEM budget model
(hpa2_tpu/analysis/vmem.py): block-width budgets that used to fail
only at Mosaic compile time on a live TPU tunnel, model/engine
consistency, and the streaming kernel's structural invariants — the
per-cycle hot loop gains no ops and no DMA from streaming (copies live
at window boundaries only)."""

import pytest

from hpa2_tpu.analysis.vmem import (
    VMEM_CAP_BYTES, budget_table, vmem_budget)
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import _init_state


def _bench_config():
    # bench.py's workload shape (8-node systems, robust semantics)
    return SystemConfig(num_procs=8, msg_buffer_size=16,
                        semantics=Semantics().robust())


# ceilings for the recursively counted per-cycle jaxpr eqns at the
# bench shape (bb=8); streaming must not grow the hot loop — a rising
# count here is a perf regression even when the tests stay green
_CYCLE_OPS_BASELINE = {False: 2172, True: 2194}


class TestBudgets:
    @pytest.mark.parametrize("block", [512, 1024, 2048])
    def test_streaming_bench_shape_fits(self, block):
        # the PERF.md lever shape for wide blocks: window 32, gate off
        bud = vmem_budget(_bench_config(), block, 32,
                          snapshots=False, gate=False, stream=True)
        assert bud.fits, (
            f"streaming block {block} predicted over the VMEM cap by "
            f"{-bud.headroom_bytes} bytes"
        )

    @pytest.mark.parametrize("block", [512, 1024])
    def test_streaming_gated_fits(self, block):
        bud = vmem_budget(_bench_config(), block, 32,
                          snapshots=False, gate=True, stream=True)
        assert bud.fits

    def test_streaming_beats_legacy_under_gate(self):
        cfg = _bench_config()
        s = vmem_budget(cfg, 1024, 32, gate=True, stream=True)
        l = vmem_budget(cfg, 1024, 32, gate=True, stream=False)
        assert s.total_rows < l.total_rows

    def test_window_scales_scratch_not_operands(self):
        cfg = _bench_config()
        small = vmem_budget(cfg, 512, 8, stream=True)
        large = vmem_budget(cfg, 512, 64, stream=True)
        assert small.operand_rows == large.operand_rows
        assert large.scratch_rows > small.scratch_rows

    def test_cap_constant(self):
        assert VMEM_CAP_BYTES == 16 * 1024 * 1024

    def test_budget_table_renders(self):
        out = budget_table(_bench_config())
        assert "block" in out and "stream" in out and "legacy" in out


class TestModelEngineConsistency:
    @pytest.mark.parametrize("packed", [False, True])
    @pytest.mark.parametrize("snapshots", [False, True])
    @pytest.mark.parametrize("n", [8, 33])
    def test_rows_match_init_state(self, snapshots, n, packed):
        # every plane the engine actually allocates is in the model
        # with the exact rows/lane AND the exact bytes/lane (dtype-
        # aware), and vice versa
        cfg = SystemConfig(num_procs=n, cache_size=2, mem_size=4,
                           semantics=Semantics().robust())
        bud = vmem_budget(cfg, 8, 4, snapshots=snapshots, packed=packed)
        state = _init_state(cfg, 8, snapshots=snapshots, packed=packed)
        want = {k: v.size // 8 for k, v in state.items()}
        assert bud.rows == want
        assert bud.carried_rows + bud.snap_rows == sum(want.values())
        want_b = {
            k: (v.size // 8) * v.dtype.itemsize for k, v in state.items()
        }
        assert bud.lane_bytes == want_b


class TestPackedPlanes:
    """ISSUE 6 acceptance: packed planes cut per-lane row bytes by
    >= 1.8x and admit >= 2x the block size at the same VMEM budget."""

    def _cfg(self):
        # the acceptance geometry: 4 nodes, 64-entry memory (256
        # addresses -> uint16 cache meta, uint8 dir meta)
        return SystemConfig(num_procs=4, cache_size=4, mem_size=64,
                            msg_buffer_size=4,
                            semantics=Semantics().robust())

    def test_row_bytes_cut_1_8x(self):
        from hpa2_tpu.analysis.vmem import state_plane_bytes

        cfg = self._cfg()
        unpacked = state_plane_bytes(cfg, packed=False)
        packed = state_plane_bytes(cfg, packed=True)
        assert unpacked >= 1.8 * packed, (
            f"packed planes cut word-plane bytes/lane only "
            f"{unpacked / packed:.2f}x (want >= 1.8x): "
            f"{unpacked} -> {packed}"
        )

    def test_admits_2x_block_at_same_budget(self):
        cfg = self._cfg()
        base = 2048
        assert vmem_budget(cfg, base, 8, stream=True).fits
        assert not vmem_budget(cfg, 2 * base, 8, stream=True).fits, (
            "geometry drifted: the unpacked layout already fits the "
            "doubled block, so the 2x-admission pin is vacuous"
        )
        assert vmem_budget(cfg, 2 * base, 8, stream=True,
                           packed=True).fits

    def test_total_bytes_unchanged_when_unpacked(self):
        # dtype-aware accounting is a refinement, not a re-model: with
        # every plane int32 it reproduces the old rows*4 figure
        bud = vmem_budget(_bench_config(), 1024, 32, stream=True)
        assert bud.total_bytes == bud.total_rows * 1024 * 4


class TestNodeSharding:
    """ISSUE 7 acceptance: the model at ``node_shards=N`` reports the
    per-shard geometry (num_procs/N local nodes), and the max-fitting
    block at least doubles from 1 -> 2 shards on the bench shape."""

    def test_per_shard_rows_mirror_plane_shapes(self):
        from hpa2_tpu.analysis.vmem import _plane_rows

        cfg = _bench_config()
        full = _plane_rows(cfg, snapshots=False)
        half = _plane_rows(cfg, snapshots=False, node_shards=2)
        for f, r in full.items():
            if f in ("scalars", "msg_counts"):
                assert half[f] == r, f"{f} is replicated, not sharded"
            else:
                assert half[f] == r // 2, (
                    f"{f} must carry half its rows per shard"
                )

    def test_max_fitting_block_doubles_at_2_shards(self):
        from hpa2_tpu.analysis.vmem import max_fitting_block

        cfg = _bench_config()
        one = max_fitting_block(cfg, 32)
        two = max_fitting_block(cfg, 32, node_shards=2)
        assert two >= 2 * one, (
            f"node sharding must at least double the block ladder's "
            f"top rung: {one} -> {two}"
        )

    def test_nondividing_geometry_raises(self):
        with pytest.raises(ValueError, match="must divide"):
            vmem_budget(_bench_config(), 512, 32, node_shards=3)

    def test_table_reports_shard_geometry(self):
        out = budget_table(_bench_config(), node_shards=2)
        assert "node_shards=2" in out and "4 local nodes/shard" in out
        assert "max fitting block" in out


class TestHotLoopGuards:
    """Structural hot-loop pins, measured through the contract engine
    (analysis/contracts.py) — the single jaxpr traversal lives in
    analysis/ir.py and the same ceilings are enforced by the checked-in
    `pallas-cycle-body` / `pallas-stream-dma` contracts."""

    @pytest.mark.parametrize("snapshots", [False, True])
    def test_cycle_opcount_no_increase(self, snapshots):
        from hpa2_tpu.analysis.contracts import (
            measure_cycle_ops, registry)

        key = "eqns.snap" if snapshots else "eqns.plain"
        ops = measure_cycle_ops().values[key]
        assert ops <= _CYCLE_OPS_BASELINE[snapshots], (
            f"per-cycle op count grew: {ops} > "
            f"{_CYCLE_OPS_BASELINE[snapshots]} — the hot loop must not "
            "pay for streaming (or anything else) per cycle"
        )
        # the declarative contract carries the identical ceiling
        contract = next(
            c for c in registry() if c.name == "pallas-cycle-body")
        rules = {r.key: (r.op, r.expect) for r in contract.rules}
        assert rules[key] == ("<=", _CYCLE_OPS_BASELINE[snapshots])

    def test_streaming_dma_outside_quiescence_loop(self):
        # copies live at window boundaries only: the while-to-
        # quiescence loop's jaxpr must contain no DMA primitives,
        # while the kernel overall must stream (>=1 dma_start)
        from hpa2_tpu.analysis.contracts import measure_stream_dma

        got = measure_stream_dma().values
        assert got["kernels"] >= 1, "streaming runner lost its pallas_call"
        assert got["dma_start.total"] >= 2, (
            "expected warm-up + prefetch dma_start")
        assert got["dma.in_while"] == 0
