"""Cross-backend equivalence: the effective transition tables extracted
from the spec engine, the JAX engine, and the native C++ engine must
all match the declarative table, row for row.

This is the static-analysis counterpart of the dynamic differential
tests: instead of whole traces, every declared (state, event,
guard-case) row is probed as a single concrete transition on each
backend, so a divergence names the exact protocol row rather than a
trace that eventually disagrees.
"""

import pytest

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.extract import diff_backend, probeable_rows
from hpa2_tpu.analysis.table import build_table

SEMS = {
    "default": Semantics(),
    "robust": Semantics().robust(),
    "head": Semantics().head_quirks(),
}


def _assert_zero_diffs(diffs):
    assert not diffs, "\n".join(diffs[:30])


@pytest.mark.parametrize("name", sorted(SEMS))
def test_spec_matches_declared_table(name):
    _assert_zero_diffs(diff_backend(build_table(SEMS[name]), "spec"))


@pytest.mark.parametrize("name", ["default", "robust"])
def test_jax_matches_declared_table(name):
    # head excluded: the JAX backend refuses to build the overloaded
    # notify quirk (step.py raises at trace time)
    _assert_zero_diffs(diff_backend(build_table(SEMS[name]), "jax"))


@pytest.mark.parametrize("name", ["default", "robust"])
def test_pallas_matches_declared_table(name):
    # head excluded for the same reason as jax: build_cycle raises on
    # the overloaded notify quirk.  Each probe runs one cycle of the
    # real kernel program (interpret mode) over staged packed planes,
    # so this additionally pins the wire-word packing and the
    # candidate-grid delivery against the declared table.
    _assert_zero_diffs(diff_backend(build_table(SEMS[name]), "pallas"))


@pytest.mark.parametrize("name", sorted(SEMS))
def test_native_matches_declared_table(name):
    from hpa2_tpu import native

    try:
        native.ensure_built()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    _assert_zero_diffs(diff_backend(build_table(SEMS[name]), "native"))


def test_probe_coverage_is_total():
    """Every reachable declared row must be exercised by a probe — a
    silently skipped row would make zero-diffs vacuous."""
    from hpa2_tpu.analysis.extract import scenario_for

    for name, sem in SEMS.items():
        rows = probeable_rows(build_table(sem))
        assert len(rows) >= 100, (name, len(rows))
        skipped = [r.key for r in rows if scenario_for(r) is None]
        assert not skipped, (name, skipped)
