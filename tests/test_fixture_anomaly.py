"""Exhaustive reachability proof for the test_4/run_1/core_2 anomaly.

The fixture tests/test_4/run_1/core_2_output.txt reports, for address
0x20 (home node 2, block 0):

    memory = 40, directory = U with empty sharer set,
    cache line = {0x20, 40, INVALID}

The transactions touching 0x20 in that run are exactly (from the
paired instruction_order.txt and the four core traces):

    order 13: P2 RD 0x20      order 22: P1 RD 0x20
    order 27: P3 RD 0x20      order 28: P3 WR 0x20 99

and no node ever evicts a 0x20 line (each holder's later accesses are
hits or the node's trace ends).  This model checker explores EVERY
interleaving of (a) instruction issue respecting only per-node program
order, (b) per-receiver-FIFO message delivery, and (c) dump timing
(every post-completion state of P2 is a legal dump point), under the
reference protocol handlers (assignment.c:187-566).

Result (asserted below): no reachable P2 dump state has directory U
with a cache line INVALID/40 — the only INVALID/40 states carry
directory EM{3} or S{1,3}.  Hence the fixture's directory row cannot
come from any execution of the shipped protocol on this trace: the
fixture set is internally inconsistent (core_2's dump presumably
captured from a different execution than the paired order log).  The
parity gate in test_spec_parity.py pins this node accordingly.
"""

import pytest

M, E, S, I = "M", "E", "S", "I"
EM, SS, U = "EM", "S", "U"

HOME = 2          # home node of 0x20
WRITE_VAL = 99    # P3's write
INIT_MEM = 40     # initial memory value of block 0 at node 2
PROGRAMS = {1: ["R"], 2: ["R"], 3: ["R", "W"]}


def _freeze(st):
    return (
        st["dir"], st["sh"], st["mem"], tuple(st["line"]),
        tuple(st["wait"]), tuple(st["pc"]),
        tuple(st["box"][i] for i in range(4)),
    )


def _clone(st):
    return {
        "dir": st["dir"], "sh": st["sh"], "mem": st["mem"],
        "line": list(st["line"]), "wait": list(st["wait"]),
        "pc": list(st["pc"]), "box": [st["box"][i] for i in range(4)],
    }


def _send(st, rcv, msg):
    st["box"][rcv] = st["box"][rcv] + (msg,)


def _handle(st, rcv, msg):
    t = msg[0]
    if t == "READ_REQUEST":
        snd = msg[1]
        if st["dir"] == U:
            st["dir"], st["sh"] = EM, frozenset({snd})
            _send(st, snd, ("REPLY_RD", st["mem"], 2))
        elif st["dir"] == SS:
            st["sh"] = st["sh"] | {snd}
            _send(st, snd, ("REPLY_RD", st["mem"], 0))
        else:
            owner = min(st["sh"])
            if owner == snd:
                _send(st, snd, ("REPLY_RD", st["mem"], 2))
            else:
                _send(st, owner, ("WRITEBACK_INT", snd))
                st["dir"], st["sh"] = SS, st["sh"] | {snd}
    elif t == "REPLY_RD":
        _, val, flag = msg
        st["line"][rcv] = (val, E if flag == 2 else S)
        st["wait"][rcv] = False
    elif t == "WRITEBACK_INT":
        req = msg[1]
        ln = st["line"][rcv]
        if ln and ln[1] in (M, E):
            _send(st, HOME, ("FLUSH", ln[0], req))
            if req != HOME:
                _send(st, req, ("FLUSH", ln[0], req))
            st["line"][rcv] = (ln[0], S)
    elif t == "FLUSH":
        _, val, req = msg
        if rcv == HOME:
            st["mem"] = val
        if rcv == req:
            st["line"][rcv] = (val, S)
            st["wait"][rcv] = False
    elif t == "UPGRADE":
        snd = msg[1]
        sh = st["sh"] - {snd} if st["dir"] == SS else frozenset()
        _send(st, snd, ("REPLY_ID", sh))
        st["dir"], st["sh"] = EM, frozenset({snd})
    elif t == "REPLY_ID":
        sh = msg[1]
        ln = st["line"][rcv]
        if ln:
            if ln[1] != M:
                st["line"][rcv] = (WRITE_VAL, M)
            for i in sh:
                if i != rcv:
                    _send(st, i, ("INV",))
        st["wait"][rcv] = False
    elif t == "INV":
        ln = st["line"][rcv]
        if ln and ln[1] in (S, E):
            st["line"][rcv] = (ln[0], I)
    elif t == "WRITE_REQUEST":
        snd = msg[1]
        if st["dir"] == U:
            st["dir"], st["sh"] = EM, frozenset({snd})
            _send(st, snd, ("REPLY_WR",))
        elif st["dir"] == SS:
            _send(st, snd, ("REPLY_ID", st["sh"] - {snd}))
            st["dir"], st["sh"] = EM, frozenset({snd})
        else:
            owner = min(st["sh"])
            if owner == snd:
                _send(st, snd, ("REPLY_WR",))
            else:
                _send(st, owner, ("WRITEBACK_INV", snd))
                st["sh"] = frozenset({snd})
    elif t == "REPLY_WR":
        st["line"][rcv] = (WRITE_VAL, M)
        st["wait"][rcv] = False
    elif t == "WRITEBACK_INV":
        req = msg[1]
        ln = st["line"][rcv]
        if ln and ln[1] in (M, E):
            _send(st, HOME, ("FLUSH_INVACK", ln[0], req))
            if req != HOME:
                _send(st, req, ("FLUSH_INVACK", ln[0], req))
            st["line"][rcv] = (ln[0], I)
    elif t == "FLUSH_INVACK":
        _, val, req = msg
        if rcv == HOME:
            st["mem"] = val
            st["dir"], st["sh"] = EM, frozenset({req})
        if rcv == req:
            st["line"][rcv] = (WRITE_VAL, M)
            st["wait"][rcv] = False


def explore():
    init = {
        "dir": U, "sh": frozenset(), "mem": INIT_MEM,
        "line": [None] * 4, "wait": [False] * 4, "pc": [0] * 4,
        "box": [(), (), (), ()],
    }
    seen, stack, p2_dump_states = set(), [init], set()
    while stack:
        st = stack.pop()
        key = _freeze(st)
        if key in seen:
            continue
        seen.add(key)
        # every post-completion state of P2 is a legal dump point
        if st["pc"][2] == 1 and not st["wait"][2]:
            p2_dump_states.add((st["dir"], st["sh"], st["mem"], st["line"][2]))
        # issue
        for p, prog in PROGRAMS.items():
            if st["pc"][p] >= len(prog) or st["wait"][p]:
                continue
            op = prog[st["pc"][p]]
            st2 = _clone(st)
            if op == "R":
                ln = st2["line"][p]
                if not (ln and ln[1] != I):
                    _send(st2, HOME, ("READ_REQUEST", p))
                    st2["wait"][p] = True
                    st2["line"][p] = (0, I)  # placeholder fill
            else:
                ln = st2["line"][p]
                if ln and ln[1] != I:
                    if ln[1] in (M, E):
                        st2["line"][p] = (WRITE_VAL, M)
                    else:
                        _send(st2, HOME, ("UPGRADE", p))
                        st2["line"][p] = (WRITE_VAL, M)
                        st2["wait"][p] = True
                else:
                    _send(st2, HOME, ("WRITE_REQUEST", p))
                    st2["wait"][p] = True
                    st2["line"][p] = (0, I)
            st2["pc"][p] += 1
            stack.append(st2)
        # deliver (head of any mailbox — per-receiver FIFO)
        for rcv in range(4):
            if not st["box"][rcv]:
                continue
            st2 = _clone(st)
            msg = st2["box"][rcv][0]
            st2["box"][rcv] = st2["box"][rcv][1:]
            _handle(st2, rcv, msg)
            stack.append(st2)
    return seen, p2_dump_states


def test_fixture_state_unreachable():
    seen, p2_states = explore()
    assert len(seen) > 300  # sanity: the space was actually explored
    fixture_like = [
        s for s in p2_states
        if s[0] == U and s[2] == INIT_MEM and s[3] == (INIT_MEM, I)
    ]
    assert fixture_like == [], (
        "fixture state became reachable — the documented anomaly no "
        f"longer holds: {fixture_like}"
    )
    # the states the protocol CAN produce with P2's line INVALID/40:
    reachable = {
        (s[0], tuple(sorted(s[1])))
        for s in p2_states
        if s[3] == (INIT_MEM, I)
    }
    assert reachable == {(EM, (3,)), (SS, (1, 3))}
