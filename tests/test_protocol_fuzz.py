"""Slow tier: cross-protocol differential fuzzing of the analyzer.

The curated 12-mutation self-test (tests/test_analysis.py) proves the
analyzer catches twelve KNOWN defect shapes in the MESI table.  This
suite samples the space between them: hundreds of seeded random
corruptions per protocol (MESI/MOESI/MESIF), each of which must be
caught — by the static table checks, by the spec probe diff, or by
the JAX probe diff.  One missed corruption is one protocol bug the
differential harness would wave through; the assertion is zero.

Runs under scripts/run_slow.sh (-m slow), not the tier-1 gate.
"""

from __future__ import annotations

import pytest

from hpa2_tpu.config import Semantics
from hpa2_tpu.analysis.mutate import run_fuzz

FUZZ_COUNT = 150


@pytest.mark.slow
@pytest.mark.parametrize("protocol", ["mesi", "moesi", "mesif"])
@pytest.mark.parametrize("semname", ["default", "robust"])
def test_every_random_corruption_is_caught(protocol, semname):
    sem = Semantics() if semname == "default" else Semantics().robust()
    results = run_fuzz(sem, protocol, seed=2024, count=FUZZ_COUNT)
    missed = [r.name for r in results if not r.caught]
    assert not missed, (
        f"[{semname}/{protocol}] analyzer missed "
        f"{len(missed)}/{FUZZ_COUNT} corruptions: {missed[:10]}")


@pytest.mark.slow
def test_fuzz_exercises_both_catchers():
    """The sample must land on both sides of the static/behavioral
    boundary, or the fuzz run silently degenerates into a test of one
    catcher."""
    results = run_fuzz(Semantics().robust(), "moesi", seed=7, count=80)
    by = {r.caught_by for r in results}
    assert "static" in by and "spec-diff" in by, by


@pytest.mark.slow
def test_fuzz_is_deterministic():
    """Same seed, same corruption stream — a failure must be
    replayable from the (seed, count) pair alone."""
    a = run_fuzz(Semantics(), "mesif", seed=3, count=25, with_jax=False)
    b = run_fuzz(Semantics(), "mesif", seed=3, count=25, with_jax=False)
    assert [r.name for r in a] == [r.name for r in b]
