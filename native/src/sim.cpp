// Engine implementation — see sim.hpp for the design overview.
// Protocol semantics mirror hpa2_tpu/models/spec_engine.py case by
// case (reference behavior: /root/reference/assignment.c:187-697).

#include "sim.hpp"

#include <omp.h>
#include <sched.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <mutex>
#include <thread>
#include <sstream>
#include <stdexcept>

namespace hpa2 {

namespace {

inline int home_of(const Config& c, int32_t addr) { return addr / c.mem; }
inline int block_of(const Config& c, int32_t addr) { return addr % c.mem; }
inline int cindex_of(const Config& c, int32_t addr) { return addr % c.cache; }

inline Sharers bit(int p) { return Sharers(1) << p; }
inline bool test_bit(Sharers s, int p) { return (s >> p) & 1; }
inline int popcount(Sharers s) { return __builtin_popcountll(s); }
inline int find_owner(Sharers s) {
  return s ? __builtin_ctzll(s) : -1;
}

inline double mono_seconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct NodeState {
  std::vector<CacheLine> cache;
  std::vector<int32_t> memory;
  std::vector<DirEntry> directory;
  const std::vector<Instr>* trace = nullptr;
  size_t pc = 0;
  bool waiting = false;
  int32_t pending = 0;

  void init(const Config& cfg, int id, const std::vector<Instr>& tr) {
    cache.assign(cfg.cache, CacheLine{});
    memory.resize(cfg.mem);
    for (int i = 0; i < cfg.mem; ++i) memory[i] = (20 * id + i) % 256;
    directory.assign(cfg.mem, DirEntry{});
    trace = &tr;
  }

  bool trace_done() const { return pc >= trace->size(); }

  NodeDump dump() const {
    NodeDump d;
    for (auto v : memory) d.memory.push_back(v);
    for (auto& e : directory) {
      d.dir_state.push_back(e.state);
      d.dir_sharers.push_back(e.sharers);
    }
    for (auto& l : cache) {
      d.cache_addr.push_back(l.addr);
      d.cache_value.push_back(l.value);
      d.cache_state.push_back(l.state);
    }
    return d;
  }
};

// handleCacheReplacement (spec_engine._replace; assignment.c:742-773)
template <class SendFn>
void replace_line(const Config& cfg, int self, const CacheLine& line,
                  SendFn&& send) {
  if (line.state == CacheSt::I || line.addr < 0) return;
  int home = home_of(cfg, line.addr);
  Msg m{};
  m.sender = self;
  m.addr = line.addr;
  m.second = -1;
  if (line.state == CacheSt::M) {
    m.type = EVICT_MODIFIED;
    m.value = line.value;
  } else {
    m.type = EVICT_SHARED;
  }
  send(home, m);
}

// the 13-case protocol switch (spec_engine._handle)
template <class SendFn>
void handle_msg(const Config& cfg, int self, NodeState& n, const Msg& msg,
                SendFn&& send) {
  const int home = home_of(cfg, msg.addr);
  const int blk = block_of(cfg, msg.addr);
  CacheLine& line = n.cache[cindex_of(cfg, msg.addr)];
  DirEntry* dir = (self == home) ? &n.directory[blk] : nullptr;
  const bool line_match = line.addr == msg.addr;
  const bool line_me =
      line.state == CacheSt::M || line.state == CacheSt::E;

  auto reply = [&](int recv, Msg m) { send(recv, m); };

  switch (msg.type) {
    case READ_REQUEST: {
      Msg r{};
      r.type = REPLY_RD;
      r.sender = self;
      r.addr = msg.addr;
      r.value = n.memory[blk];
      r.second = -1;
      if (dir->state == DirSt::U) {
        dir->state = DirSt::EM;
        dir->sharers = bit(msg.sender);
        r.sharers = 2;  // exclusive flag (assignment.c:201)
        reply(msg.sender, r);
      } else if (dir->state == DirSt::S) {
        dir->sharers |= bit(msg.sender);
        r.sharers = 0;
        reply(msg.sender, r);
      } else {
        int owner = find_owner(dir->sharers);
        if (owner == msg.sender) {
          r.sharers = 2;
          reply(msg.sender, r);
        } else {
          Msg f{};
          f.type = WRITEBACK_INT;
          f.sender = self;
          f.addr = msg.addr;
          f.second = msg.sender;
          send(owner, f);
          dir->state = DirSt::S;  // optimistic (assignment.c:230-231)
          dir->sharers |= bit(msg.sender);
        }
      }
      break;
    }

    case REPLY_RD: {
      if (line.addr >= 0 && !line_match && line.state != CacheSt::I)
        replace_line(cfg, self, line, send);
      line.addr = msg.addr;
      line.value = msg.value;
      line.state = (msg.sharers == 2) ? CacheSt::E : CacheSt::S;
      n.waiting = false;
      break;
    }

    case WRITEBACK_INT: {
      if (line_match && line_me) {
        Msg f{};
        f.type = FLUSH;
        f.sender = self;
        f.addr = msg.addr;
        f.value = line.value;
        f.second = msg.second;
        send(home, f);
        if (msg.second != home) send(msg.second, f);
        line.state = CacheSt::S;
      } else if (cfg.nack) {
        Msg k{};
        k.type = NACK;
        k.sender = self;
        k.addr = msg.addr;
        k.sharers = 0;  // read intervention
        k.second = msg.second;
        send(home, k);
      }
      break;
    }

    case FLUSH: {
      if (self == home) n.memory[blk] = msg.value;
      if (self == msg.second) {
        if (line.addr >= 0 && !line_match && line.state != CacheSt::I)
          replace_line(cfg, self, line, send);
        line.addr = msg.addr;
        line.value = msg.value;
        line.state = CacheSt::S;
        n.waiting = false;
      }
      break;
    }

    case UPGRADE: {
      Msg r{};
      r.type = REPLY_ID;
      r.sender = self;
      r.addr = msg.addr;
      r.second = -1;
      r.sharers =
          (dir->state == DirSt::S) ? (dir->sharers & ~bit(msg.sender)) : 0;
      reply(msg.sender, r);
      dir->state = DirSt::EM;
      dir->sharers = bit(msg.sender);
      break;
    }

    case REPLY_ID: {
      bool fan_out = true;
      if (line_match && line.state != CacheSt::M) {
        line.value = n.pending;
        line.state = CacheSt::M;
      } else if (!line_match) {
        fan_out = false;  // replaced while waiting (assignment.c:339-347)
      }
      if (fan_out) {
        for (int i = 0; i < cfg.nodes; ++i) {
          if (i != self && test_bit(msg.sharers, i)) {
            Msg inv{};
            inv.type = INV;
            inv.sender = self;
            inv.addr = msg.addr;
            inv.second = -1;
            send(i, inv);
          }
        }
      }
      n.waiting = false;
      break;
    }

    case INV: {
      if (line_match &&
          (line.state == CacheSt::S || line.state == CacheSt::E))
        line.state = CacheSt::I;
      break;
    }

    case WRITE_REQUEST: {
      if (cfg.eager_write_request_memory) n.memory[blk] = msg.value;
      if (dir->state == DirSt::U) {
        dir->state = DirSt::EM;
        dir->sharers = bit(msg.sender);
        Msg r{};
        r.type = REPLY_WR;
        r.sender = self;
        r.addr = msg.addr;
        r.second = -1;
        reply(msg.sender, r);
      } else if (dir->state == DirSt::S) {
        Msg r{};
        r.type = REPLY_ID;
        r.sender = self;
        r.addr = msg.addr;
        r.sharers = dir->sharers & ~bit(msg.sender);
        r.second = -1;
        reply(msg.sender, r);
        dir->state = DirSt::EM;
        dir->sharers = bit(msg.sender);
      } else {
        int owner = find_owner(dir->sharers);
        if (owner == msg.sender) {
          Msg r{};
          r.type = REPLY_WR;
          r.sender = self;
          r.addr = msg.addr;
          r.second = -1;
          reply(msg.sender, r);
        } else {
          Msg f{};
          f.type = WRITEBACK_INV;
          f.sender = self;
          f.addr = msg.addr;
          f.second = msg.sender;
          send(owner, f);
          dir->sharers = bit(msg.sender);  // state stays EM (c:429)
        }
      }
      break;
    }

    case REPLY_WR: {
      line.addr = msg.addr;
      line.value = n.pending;
      line.state = CacheSt::M;
      n.waiting = false;
      break;
    }

    case WRITEBACK_INV: {
      if (line_match && line_me) {
        Msg f{};
        f.type = FLUSH_INVACK;
        f.sender = self;
        f.addr = msg.addr;
        f.value = line.value;
        f.second = msg.second;
        send(home, f);
        if (msg.second != home) send(msg.second, f);
        line.state = CacheSt::I;
      } else if (cfg.nack) {
        Msg k{};
        k.type = NACK;
        k.sender = self;
        k.addr = msg.addr;
        k.sharers = 1;  // write intervention
        k.second = msg.second;
        send(home, k);
      }
      break;
    }

    case FLUSH_INVACK: {
      if (self == home) {
        n.memory[blk] = msg.value;
        dir->state = DirSt::EM;
        dir->sharers = bit(msg.second);
      }
      if (self == msg.second) {
        line.addr = msg.addr;
        line.value =
            cfg.flush_invack_fills_old_value ? msg.value : n.pending;
        line.state = CacheSt::M;
        n.waiting = false;
      }
      break;
    }

    case EVICT_SHARED: {
      if (self == home) {
        // the home branch wins even when the message is HEAD's
        // overloaded upgrade-notify arriving at a home-that-shares —
        // destructively re-interpreted as an eviction, exactly the
        // assignment.c:499-521 livelock mechanism (SURVEY.md §6.3)
        if (test_bit(dir->sharers, msg.sender)) {
          dir->sharers &= ~bit(msg.sender);
          int remaining = popcount(dir->sharers);
          if (remaining == 0) {
            dir->state = DirSt::U;
          } else if (remaining == 1 && dir->state == DirSt::S) {
            dir->state = DirSt::EM;
            Msg u{};
            u.type = cfg.overloaded_evict_shared_notify
                         ? EVICT_SHARED
                         : UPGRADE_NOTIFY;
            u.sender = self;
            u.addr = msg.addr;
            u.second = -1;
            send(find_owner(dir->sharers), u);
          }
        }
      } else if (cfg.overloaded_evict_shared_notify) {
        // HEAD's non-home branch (assignment.c:522-538): sender==home
        // means "you are the last sharer — upgrade S to E"
        if (msg.sender == home && line_match &&
            line.state == CacheSt::S)
          line.state = CacheSt::E;
      }
      // a non-home EVICT_SHARED cannot occur in fixture semantics
      // (the notify is the distinct UPGRADE_NOTIFY type)
      break;
    }

    case UPGRADE_NOTIFY: {
      if (msg.sender == home && line_match && line.state == CacheSt::S)
        line.state = CacheSt::E;
      break;
    }

    case EVICT_MODIFIED: {
      n.memory[blk] = msg.value;
      if (dir->state == DirSt::EM && test_bit(dir->sharers, msg.sender)) {
        dir->sharers = 0;
        dir->state = DirSt::U;
      }
      break;
    }

    case NACK: {
      int requester = msg.second;
      if (msg.sharers == 0) {  // re-serve read from memory
        dir->state = DirSt::S;
        dir->sharers |= bit(requester);
        Msg r{};
        r.type = REPLY_RD;
        r.sender = self;
        r.addr = msg.addr;
        r.value = n.memory[blk];
        r.sharers = 0;
        r.second = -1;
        send(requester, r);
      } else {  // re-serve write
        dir->state = DirSt::EM;
        dir->sharers = bit(requester);
        Msg r{};
        r.type = REPLY_WR;
        r.sender = self;
        r.addr = msg.addr;
        r.second = -1;
        send(requester, r);
      }
      break;
    }
  }
}

// instruction issue (spec_engine._issue; assignment.c:590-697)
template <class SendFn>
void issue_one(const Config& cfg, int self, NodeState& n, SendFn&& send) {
  const Instr& ins = (*n.trace)[n.pc++];
  const int home = home_of(cfg, ins.addr);
  CacheLine& line = n.cache[cindex_of(cfg, ins.addr)];
  const bool hit = line.addr == ins.addr && line.state != CacheSt::I;

  if (!ins.write) {
    if (hit) return;
    if (line.addr >= 0 && line.state != CacheSt::I)
      replace_line(cfg, self, line, send);
    Msg r{};
    r.type = READ_REQUEST;
    r.sender = self;
    r.addr = ins.addr;
    r.second = -1;
    send(home, r);
    n.waiting = true;
    line.state = CacheSt::I;  // placeholder (assignment.c:626-628)
    line.addr = ins.addr;
    line.value = 0;
  } else {
    n.pending = ins.value;
    if (hit) {
      if (line.state == CacheSt::M || line.state == CacheSt::E) {
        line.value = ins.value;
        line.state = CacheSt::M;  // silent E->M
      } else {  // SHARED: write applied locally before REPLY_ID
        Msg u{};
        u.type = UPGRADE;
        u.sender = self;
        u.addr = ins.addr;
        u.second = -1;
        send(home, u);
        line.value = ins.value;
        line.state = CacheSt::M;
        n.waiting = true;
      }
    } else {
      if (line.addr >= 0 && line.state != CacheSt::I)
        replace_line(cfg, self, line, send);
      Msg r{};
      r.type = WRITE_REQUEST;
      r.sender = self;
      r.addr = ins.addr;
      r.value = ins.value;
      r.second = -1;
      send(home, r);
      n.waiting = true;
      line.state = CacheSt::I;
      line.addr = ins.addr;
      line.value = 0;
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------
// Single-transition probe (analysis/extract.py cross-backend diff)
// ---------------------------------------------------------------------
//
// Stages one node exactly as described by the packed `in` layout,
// feeds it one message (handle_msg) or one instruction (issue_one),
// and reports the node's post-state plus every emission.  The layout
// is fixed by hpa2_tpu/analysis/extract.py:_native_packed — 22 input
// slots; output is 8 header slots then 5 per emission.

int probe_transition(const Config& cfg, const long long* in,
                     long long* out, int out_cap) {
  const int receiver = (int)in[0];
  if (receiver < 0 || receiver >= cfg.nodes) return -1;
  if (out_cap < 8) return -2;

  std::vector<std::vector<Instr>> traces(cfg.nodes);
  if (in[1]) {
    Instr ins{};
    ins.write = in[2] != 0;
    ins.addr = (int32_t)in[3];
    ins.value = (int32_t)in[4];
    traces[receiver].push_back(ins);
  }
  std::vector<NodeState> nodes(cfg.nodes);
  for (int i = 0; i < cfg.nodes; ++i) nodes[i].init(cfg, i, traces[i]);

  NodeState& n = nodes[receiver];
  const int li = (int)in[11];
  if (li < 0 || li >= cfg.cache) return -1;
  n.cache[li].addr = (int32_t)in[12];
  n.cache[li].value = (int32_t)in[13];
  n.cache[li].state = (CacheSt)(int8_t)in[14];
  const int blk = (int)in[15];
  const int mblk = (int)in[18];
  if (blk < 0 || blk >= cfg.mem || mblk < 0 || mblk >= cfg.mem) return -1;
  n.directory[blk].state = (DirSt)(int8_t)in[16];
  n.directory[blk].sharers = (Sharers)in[17];
  n.memory[mblk] = (int32_t)in[19];
  n.pending = (int32_t)in[20];
  n.waiting = in[21] != 0;

  struct Emitted {
    int recv;
    Msg m;
  };
  std::vector<Emitted> emits;
  auto send = [&](int recv, const Msg& m) { emits.push_back({recv, m}); };

  if (in[1]) {
    issue_one(cfg, receiver, n, send);
  } else {
    Msg msg{};
    msg.type = (int8_t)in[5];
    msg.sender = (int32_t)in[6];
    msg.addr = (int32_t)in[7];
    msg.value = (int32_t)in[8];
    msg.sharers = (Sharers)in[9];
    msg.second = (int32_t)in[10];
    handle_msg(cfg, receiver, n, msg, send);
  }

  if (out_cap < 8 + 5 * (int)emits.size()) return -2;
  out[0] = n.cache[li].addr;
  out[1] = n.cache[li].value;
  out[2] = (long long)n.cache[li].state;
  out[3] = (long long)n.directory[blk].state;
  out[4] = (long long)n.directory[blk].sharers;
  out[5] = n.memory[mblk];
  out[6] = n.waiting ? 1 : 0;
  out[7] = (long long)emits.size();
  for (size_t i = 0; i < emits.size(); ++i) {
    long long* e = out + 8 + 5 * i;
    e[0] = emits[i].recv;
    e[1] = (long long)emits[i].m.type;
    e[2] = emits[i].m.value;
    e[3] = emits[i].m.second;
    e[4] = (long long)emits[i].m.sharers;
  }
  return 0;
}

// ---------------------------------------------------------------------
// Deterministic lockstep engine (spec_engine.SpecEngine.step)
// ---------------------------------------------------------------------

static std::string fmt_msg_recv(int proc, const Msg& m) {
  char buf[96];
  std::snprintf(buf, sizeof buf,
                "Processor %d msg from: %d, type: %d, address: 0x%02X",
                proc, m.sender, (int)m.type, m.addr);
  return buf;
}

static std::string fmt_msg_send(int recv, const Msg& m) {
  char buf[96];
  std::snprintf(buf, sizeof buf,
                "Processor %d sent msg to: %d, type: %d, address: 0x%02X",
                m.sender, recv, (int)m.type, m.addr);
  return buf;
}

RunResult run_lockstep(const Config& cfg,
                       const std::vector<std::vector<Instr>>& traces,
                       const std::vector<IssueRecord>* replay,
                       uint64_t max_cycles,
                       bool capture_candidates,
                       bool trace_msgs) {
  RunResult res;
  const int N = cfg.nodes;
  std::vector<NodeState> nodes(N);
  std::vector<std::deque<Msg>> mailbox(N);
  for (int i = 0; i < N; ++i) nodes[i].init(cfg, i, traces[i]);
  res.snapshots.resize(N);
  res.candidates.resize(N);
  std::vector<bool> dumped(N, false);

  size_t order_pos = 0;
  // send candidate: phase (0=handle, 1=issue) + sender for the global
  // deterministic delivery order; rejected candidates defer to the
  // sender's pending list (capacity backpressure — the lockstep analog
  // of the reference's blocking enqueue, assignment.c:715-724)
  struct Cand {
    int phase;
    int sender;
    int recv;
    Msg m;
  };
  std::vector<Cand> outbox;
  std::vector<std::vector<Cand>> pending(N);

  auto quiescent = [&]() {
    for (int i = 0; i < N; ++i)
      if (!nodes[i].trace_done() || nodes[i].waiting ||
          !mailbox[i].empty() || !pending[i].empty())
        return false;
    if (replay && order_pos < replay->size()) return false;
    return true;
  };

  uint64_t cycle = 0;
  int stall = 0;
  while (true) {
    bool all_dumped = true;
    for (int i = 0; i < N; ++i) all_dumped = all_dumped && dumped[i];
    if (quiescent() && all_dumped) break;
    if (cycle >= max_cycles) {
      res.error = "no quiescence after max cycles";
      res.counters.cycles = cycle;
      return res;
    }

    bool progress = false;
    std::vector<bool> handled(N, false);

    // 1. handle one message per node (nodes with deferred sends are
    // blocked, like a reference thread stuck inside sendMessage)
    for (int i = 0; i < N; ++i) {
      if (mailbox[i].empty() || !pending[i].empty()) continue;
      Msg m = mailbox[i].front();
      mailbox[i].pop_front();
      if (trace_msgs) res.msg_log.push_back(fmt_msg_recv(i, m));
      handle_msg(cfg, i, nodes[i], m, [&](int recv, const Msg& mm) {
        outbox.push_back(Cand{0, i, recv, mm});
      });
      handled[i] = true;
      progress = true;
    }

    // 2. issue
    if (replay) {
      if (order_pos < replay->size()) {
        const IssueRecord& rec = (*replay)[order_pos];
        NodeState& nd = nodes[rec.proc];
        if (mailbox[rec.proc].empty() && pending[rec.proc].empty() &&
            !nd.waiting && !nd.trace_done()) {
          const Instr& nxt = (*nd.trace)[nd.pc];
          if (nxt.write != rec.write || nxt.addr != rec.addr) {
            res.error = "replay order mismatch";
            return res;
          }
          res.issue_order.push_back(
              {rec.proc, nxt.write, nxt.addr, nxt.value});
          issue_one(cfg, rec.proc, nd, [&](int recv, const Msg& mm) {
            outbox.push_back(Cand{1, rec.proc, recv, mm});
          });
          res.counters.instructions++;
          order_pos++;
          progress = true;
        }
      }
    } else {
      for (int i = 0; i < N; ++i) {
        NodeState& nd = nodes[i];
        if (mailbox[i].empty() && pending[i].empty() && !nd.waiting &&
            !nd.trace_done()) {
          const Instr& nxt = (*nd.trace)[nd.pc];
          res.issue_order.push_back({i, nxt.write, nxt.addr, nxt.value});
          issue_one(cfg, i, nd, [&](int recv, const Msg& mm) {
            outbox.push_back(Cand{1, i, recv, mm});
          });
          res.counters.instructions++;
          progress = true;
        }
      }
    }

    // 3. deliver with capacity backpressure: pending (deferred) sends
    // at their original (phase, sender) positions, then this cycle's
    // new sends; accepted while the receiver has space, the rest kept
    // on the sender (blocked nodes don't act, so a node never has both
    // pending and new candidates)
    {
      std::vector<Cand> merged;
      for (int i = 0; i < N; ++i) {
        for (auto& c : pending[i]) merged.push_back(c);
        pending[i].clear();
      }
      for (auto& c : outbox) merged.push_back(c);
      outbox.clear();
      std::stable_sort(merged.begin(), merged.end(),
                       [](const Cand& a, const Cand& b) {
                         return a.phase != b.phase ? a.phase < b.phase
                                                   : a.sender < b.sender;
                       });
      for (auto& c : merged) {
        if ((int)mailbox[c.recv].size() < cfg.cap) {
          mailbox[c.recv].push_back(c.m);
          if (trace_msgs)
            res.msg_log.push_back(fmt_msg_send(c.recv, c.m));
          res.counters.messages++;
          progress = true;
        } else {
          pending[c.sender].push_back(c);
        }
      }
    }

    // 4. dump-at-local-completion (+ candidate capture)
    for (int i = 0; i < N; ++i) {
      NodeState& nd = nodes[i];
      if (nd.trace_done() && !nd.waiting && pending[i].empty()) {
        if (!dumped[i]) {
          if (mailbox[i].empty()) {
            dumped[i] = true;
            res.snapshots[i] = nd.dump();
            if (capture_candidates) res.candidates[i].push_back(res.snapshots[i]);
            progress = true;
          }
        } else if (capture_candidates && handled[i]) {
          res.candidates[i].push_back(nd.dump());
        }
      }
    }

    ++cycle;
    if (!progress) {
      if (++stall > 2) {
        res.error = "livelock (stale intervention dropped; use --robust)";
        res.counters.cycles = cycle;
        return res;
      }
    } else {
      stall = 0;
    }
  }

  res.counters.cycles = cycle;
  for (int i = 0; i < N; ++i) res.finals.push_back(nodes[i].dump());
  res.completed = true;
  return res;
}

// ---------------------------------------------------------------------
// Free-running OpenMP engine (thread-per-node, quiescence-terminating)
// ---------------------------------------------------------------------

namespace {

struct RingBox {
  std::vector<Msg> ring;
  int head = 0, tail = 0, count = 0;
  // std::mutex (pthread-backed) rather than omp_lock_t: identical
  // semantics/cost, but ThreadSanitizer intercepts pthread locks while
  // an uninstrumented libgomp's locks are invisible to it — this keeps
  // the engine race-checkable (make tsan)
  std::mutex lock;
};

}  // namespace

RunResult run_omp(const Config& cfg,
                  const std::vector<std::vector<Instr>>& traces,
                  int num_threads, bool record_order, bool trace_msgs) {
  RunResult res;
  const int N = cfg.nodes;
  if (num_threads <= 0) num_threads = N;
  std::vector<NodeState> nodes(N);
  std::vector<RingBox> box(N);
  for (int i = 0; i < N; ++i) {
    nodes[i].init(cfg, i, traces[i]);
    box[i].ring.resize(cfg.cap);
  }
  res.snapshots.resize(N);
  res.candidates.resize(N);

  // quiescence accounting: stable once all traces are exhausted, no
  // node is waiting, and no message is in flight
  std::atomic<long> inflight{0};
  std::atomic<int> undone{N};
  std::atomic<uint64_t> instr_total{0};
  // issue-interleaving record (the DEBUG_INSTR log, assignment.c:
  // 596-597): each issue reserves the next slot with one fetch_add —
  // the linearization the record/replay workflow validates against
  size_t total_instrs = 0;
  if (record_order)
    for (auto& t : traces) total_instrs += t.size();
  std::vector<IssueRecord> order_buf(total_instrs);
  std::atomic<uint64_t> issue_seq{0};
  std::mutex log_lock;
  auto log_line = [&](std::string s) {
    if (!trace_msgs) return;
    std::lock_guard<std::mutex> g(log_lock);
    res.msg_log.push_back(std::move(s));
  };
  std::atomic<bool> aborted{false};  // livelock watchdog (the
  // reference spins forever on this class; SURVEY.md §6.3).
  // Wall-clock deadline, not a yield count: sched_yield() latency
  // varies ~1000x with core count and load, so a spin budget is
  // seconds on one box and minutes on another.
  constexpr double kWatchdogSeconds = 10.0;

  auto send = [&](int recv, const Msg& m) {
    inflight.fetch_add(1, std::memory_order_relaxed);
    double spin_start = -1.0;
    for (;;) {
      box[recv].lock.lock();
      if (box[recv].count < cfg.cap) break;
      box[recv].lock.unlock();  // full: yield and retry (the
      // reference busy-waits with usleep, c:715-724)
      // watchdog: with tiny capacities blocked senders can deadlock
      // cyclically (the reference would spin forever here)
      double now = mono_seconds();
      if (spin_start < 0) spin_start = now;
      if (now - spin_start > kWatchdogSeconds)
        aborted.store(true, std::memory_order_relaxed);
      if (aborted.load(std::memory_order_relaxed)) {
        inflight.fetch_sub(1, std::memory_order_relaxed);
        return;  // run is aborting; message intentionally dropped
      }
      sched_yield();
    }
    box[recv].ring[box[recv].tail] = m;
    box[recv].tail = (box[recv].tail + 1) % cfg.cap;
    box[recv].count++;
    // log before releasing the box lock: the receiver cannot dequeue
    // until then, so every message's send line precedes its receive
    if (trace_msgs) log_line(fmt_msg_send(recv, m));
    box[recv].lock.unlock();
  };

  if (num_threads > N) num_threads = N;
  std::atomic<uint64_t> msg_total{0};
  // plain std::thread workers rather than a #pragma omp parallel
  // region: identical pool semantics, but ThreadSanitizer intercepts
  // pthread create/join while an uninstrumented libgomp's fork/join
  // barriers are invisible to it — with OMP the *entire engine* reads
  // as one big phantom race (make tsan would be useless)
  auto worker = [&](int tid, int nt) {
    // each thread owns a contiguous block of nodes and round-robins
    // them: drain-then-issue per node, exactly the reference's loop
    // shape (assignment.c:153-699) but multiplexed so any thread
    // count (1..N) works and oversubscription degrades gracefully
    const int lo = (int)((int64_t)N * tid / nt);
    const int hi = (int)((int64_t)N * (tid + 1) / nt);
    std::vector<bool> counted_done(hi - lo, false);
    std::vector<bool> snapped(hi - lo, false);
    uint64_t my_instrs = 0, my_msgs = 0;
    double idle_start = -1.0;

    auto csend = [&](int recv, const Msg& m) {
      ++my_msgs;
      send(recv, m);
    };

    for (;;) {
      if (aborted.load(std::memory_order_relaxed)) break;
      bool progressed = false;
      for (int i = lo; i < hi; ++i) {
        NodeState& nd = nodes[i];
        // drain mailbox
        for (;;) {
          box[i].lock.lock();
          if (box[i].count == 0) {
            box[i].lock.unlock();
            break;
          }
          Msg m = box[i].ring[box[i].head];
          box[i].head = (box[i].head + 1) % cfg.cap;
          box[i].count--;
          box[i].lock.unlock();
          if (trace_msgs) log_line(fmt_msg_recv(i, m));
          handle_msg(cfg, i, nd, m, csend);
          inflight.fetch_sub(1, std::memory_order_release);
          progressed = true;
        }

        if (!nd.waiting) {
          if (!nd.trace_done()) {
            if (record_order) {
              const Instr& nxt = (*nd.trace)[nd.pc];
              uint64_t slot =
                  issue_seq.fetch_add(1, std::memory_order_relaxed);
              order_buf[slot] =
                  IssueRecord{i, nxt.write, nxt.addr, nxt.value};
            }
            issue_one(cfg, i, nd, csend);
            ++my_instrs;
            progressed = true;
          } else {
            if (!snapped[i - lo]) {
              snapped[i - lo] = true;
              res.snapshots[i] = nd.dump();
            }
            if (!counted_done[i - lo]) {
              counted_done[i - lo] = true;
              undone.fetch_sub(1, std::memory_order_release);
            }
          }
        }
      }

      if (undone.load(std::memory_order_acquire) == 0 &&
          inflight.load(std::memory_order_acquire) == 0)
        break;

      if (progressed) {
        idle_start = -1.0;
      } else {
        // idle: let peers run (critical when oversubscribed) and
        // watchdog the reference's livelock class (SURVEY.md §6.3)
        double now = mono_seconds();
        if (idle_start < 0) idle_start = now;
        if (now - idle_start > kWatchdogSeconds) {
          aborted.store(true, std::memory_order_relaxed);
          break;
        }
        sched_yield();
      }
    }
    instr_total.fetch_add(my_instrs, std::memory_order_relaxed);
    msg_total.fetch_add(my_msgs, std::memory_order_relaxed);
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < num_threads; ++t)
    pool.emplace_back(worker, t, num_threads);
  worker(0, num_threads);
  for (auto& th : pool) th.join();

  if (record_order)
    res.issue_order.assign(order_buf.begin(),
                           order_buf.begin() + issue_seq.load());
  res.counters.instructions = instr_total.load();
  res.counters.messages = msg_total.load();
  if (aborted.load()) {
    // no finals: threads were torn down mid-protocol, so node state
    // is not a consistent quiescent snapshot
    res.error = "livelock watchdog fired (stale intervention dropped; "
                "use --robust)";
  } else {
    for (int i = 0; i < N; ++i) res.finals.push_back(nodes[i].dump());
    res.completed = true;
  }
  return res;
}

// ---------------------------------------------------------------------
// Synthetic workload (splitmix64)
// ---------------------------------------------------------------------

std::vector<std::vector<Instr>> gen_uniform_random(const Config& cfg,
                                                   int instrs_per_core,
                                                   uint64_t seed) {
  auto next = [](uint64_t& s) {
    s += 0x9E3779B97F4A7C15ull;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  };
  std::vector<std::vector<Instr>> out(cfg.nodes);
  for (int n = 0; n < cfg.nodes; ++n) {
    uint64_t s = seed * 1000003ull + n;
    out[n].reserve(instrs_per_core);
    for (int k = 0; k < instrs_per_core; ++k) {
      uint64_t r = next(s);
      Instr ins;
      ins.write = (r >> 40) & 1;
      ins.addr = int32_t(r % uint64_t(cfg.num_addresses()));
      ins.value = int32_t((r >> 8) % 256);
      out[n].push_back(ins);
    }
  }
  return out;
}

}  // namespace hpa2
