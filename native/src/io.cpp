// Trace loading and byte-exact state dumps.
//
// Formats are the reference's (README.md:55-68; printProcessorState at
// assignment.c:824-875) with the fixture-style binary bitVector
// rendering (SURVEY.md §6.2.1), identical to hpa2_tpu/utils/{trace,
// dump}.py.

#include "sim.hpp"

#include <cctype>
#include <cstdio>
#include <fstream>
#include <sstream>
#include <stdexcept>

namespace hpa2 {

static const char* kCacheStr[] = {"MODIFIED", "EXCLUSIVE", "SHARED",
                                  "INVALID"};
static const char* kDirStr[] = {"EM", "S", "U"};

std::vector<std::vector<Instr>> load_trace_dir(const Config& cfg,
                                               const std::string& dir) {
  std::vector<std::vector<Instr>> traces(cfg.nodes);
  for (int n = 0; n < cfg.nodes; ++n) {
    std::string path = dir + "/core_" + std::to_string(n) + ".txt";
    std::ifstream f(path);
    if (!f) throw std::runtime_error("cannot open " + path);
    std::string line;
    int lineno = 0;
    while (std::getline(f, line)) {
      ++lineno;
      // trim
      size_t b = line.find_first_not_of(" \t\r\n");
      if (b == std::string::npos) continue;  // blank
      size_t e = line.find_last_not_of(" \t\r\n");
      std::string s = line.substr(b, e - b + 1);
      if (cfg.max_instr > 0 &&
          (int)traces[n].size() >= cfg.max_instr)
        break;
      Instr ins{};
      unsigned addr;
      unsigned value;
      int used = -1;
      // trailing %n + full-consumption check: reject partial parses
      // like "RD 0xZZ" that bare sscanf would silently accept
      if (sscanf(s.c_str(), "RD %x %n", &addr, &used) == 1 &&
          used == (int)s.size() && s.rfind("RD", 0) == 0) {
        ins.write = false;
        ins.addr = (int32_t)addr;
        ins.value = 0;
      } else if ((used = -1,
                  sscanf(s.c_str(), "WR %x %u %n", &addr, &value, &used) ==
                      2) &&
                 used == (int)s.size() && s.rfind("WR", 0) == 0) {
        ins.write = true;
        ins.addr = (int32_t)addr;
        ins.value = (int32_t)(value % 256);  // %hhu wrap
      } else {
        throw std::runtime_error(path + ": malformed trace line " +
                                 std::to_string(lineno) + ": " + s);
      }
      if (ins.addr < 0 || ins.addr >= cfg.num_addresses())
        throw std::runtime_error(path + ": address out of range at line " +
                                 std::to_string(lineno));
      traces[n].push_back(ins);
    }
  }
  return traces;
}

std::vector<IssueRecord> load_instruction_order(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot open " + path);
  std::vector<IssueRecord> out;
  std::string line;
  int lineno = 0;
  while (std::getline(f, line)) {
    ++lineno;
    if (line.find_first_not_of(" \t\r\n") == std::string::npos) continue;
    int proc, value;
    char type;
    unsigned addr;
    if (sscanf(line.c_str(),
               "Processor %d: instr type=%c, address=0x%x, value=%d",
               &proc, &type, &addr, &value) != 4)
      throw std::runtime_error(path + ": malformed order line " +
                               std::to_string(lineno));
    out.push_back({proc, type == 'W', (int32_t)addr, value});
  }
  return out;
}

std::string format_instruction_order(const std::vector<IssueRecord>& recs) {
  std::string out;
  char buf[96];
  for (const auto& r : recs) {
    snprintf(buf, sizeof buf,
             "Processor %d: instr type=%c, address=0x%02X, value=%d\n",
             r.proc, r.write ? 'W' : 'R', (unsigned)r.addr, r.value);
    out += buf;
  }
  return out;
}

static std::string binary8(Sharers s) {
  if (s >> 8)
    throw std::runtime_error(
        "sharer mask needs more than 8 binary digits; wide format "
        "required for nodes > 8");
  std::string out(8, '0');
  for (int i = 0; i < 8; ++i)
    if ((s >> i) & 1) out[7 - i] = '1';
  return out;
}

std::string format_dump(const Config& cfg, int proc, const NodeDump& d) {
  char buf[128];
  std::string out;
  if (!cfg.parity_format()) {
    // scalable wide format (mirrors hpa2_tpu/utils/dump.py:_format_wide)
    snprintf(buf, sizeof buf,
             "# hpa2 node dump (wide format) proc=%d nodes=%d mem=%d "
             "cache=%d\n",
             proc, cfg.nodes, cfg.mem, cfg.cache);
    out += buf;
    out += "[memory]\n";
    for (int i = 0; i < cfg.mem; ++i) {
      snprintf(buf, sizeof buf, "%d 0x%x %d\n", i, proc * cfg.mem + i,
               d.memory[i]);
      out += buf;
    }
    out += "[directory]\n";
    for (int i = 0; i < cfg.mem; ++i) {
      int words = (cfg.nodes + 31) / 32;
      std::string hexwords;
      for (int w = 0; w < words; ++w) {
        char hb[16];
        snprintf(hb, sizeof hb, "%08x",
                 (uint32_t)((d.dir_sharers[i] >> (32 * w)) & 0xFFFFFFFFu));
        if (w) hexwords += ",";
        hexwords += hb;
      }
      snprintf(buf, sizeof buf, "%d 0x%x %s %s\n", i, proc * cfg.mem + i,
               kDirStr[(int)d.dir_state[i]], hexwords.c_str());
      out += buf;
    }
    out += "[cache]\n";
    for (int i = 0; i < cfg.cache; ++i) {
      if (d.cache_addr[i] < 0)
        snprintf(buf, sizeof buf, "%d - %d %s\n", i, d.cache_value[i],
                 kCacheStr[(int)d.cache_state[i]]);
      else
        snprintf(buf, sizeof buf, "%d 0x%x %d %s\n", i, d.cache_addr[i],
                 d.cache_value[i], kCacheStr[(int)d.cache_state[i]]);
      out += buf;
    }
    return out;
  }

  out += "=======================================\n";
  snprintf(buf, sizeof buf, " Processor Node: %d\n", proc);
  out += buf;
  out += "=======================================\n\n";

  out += "-------- Memory State --------\n";
  out += "| Index | Address |   Value  |\n";
  out += "|----------------------------|\n";
  for (int i = 0; i < cfg.mem; ++i) {
    snprintf(buf, sizeof buf, "|  %3d  |  0x%02X   |  %5d   |\n", i,
             (proc << 4) + i, d.memory[i]);
    out += buf;
  }
  out += "------------------------------\n\n";

  out += "------------ Directory State ---------------\n";
  out += "| Index | Address | State |    BitVector   |\n";
  out += "|------------------------------------------|\n";
  for (int i = 0; i < cfg.mem; ++i) {
    snprintf(buf, sizeof buf, "|  %3d  |  0x%02X   |  %2s   |   0x%s   |\n",
             i, (proc << 4) + i, kDirStr[(int)d.dir_state[i]],
             binary8(d.dir_sharers[i]).c_str());
    out += buf;
  }
  out += "--------------------------------------------\n\n";

  out += "------------ Cache State ----------------\n";
  out += "| Index | Address | Value |    State    |\n";
  out += "|---------------------------------------|\n";
  for (int i = 0; i < cfg.cache; ++i) {
    int addr = d.cache_addr[i] < 0 ? 0xFF : d.cache_addr[i];
    snprintf(buf, sizeof buf, "|  %3d  |  0x%02X   |  %3d  |  %8s \t|\n",
             i, addr, d.cache_value[i], kCacheStr[(int)d.cache_state[i]]);
    out += buf;
  }
  out += "----------------------------------------\n\n";
  return out;
}

}  // namespace hpa2
