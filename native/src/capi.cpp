// C API for the Python ctypes binding (hpa2_tpu/native.py).
//
// pybind11 is not available in this environment, so the boundary is a
// small C surface: run a trace directory (writing reference-format
// dump files) or a synthetic benchmark, returning counters through an
// out-struct.

#include "sim.hpp"

#include <chrono>
#include <cstring>
#include <fstream>

using namespace hpa2;

extern "C" {

struct Hpa2Result {
  unsigned long long instructions;
  unsigned long long messages;
  unsigned long long cycles;
  double seconds;
  int ok;          // 1 = completed (quiescent)
  char error[256];
};

static void set_err(Hpa2Result* r, const std::string& e) {
  r->ok = 0;
  std::strncpy(r->error, e.c_str(), sizeof(r->error) - 1);
  r->error[sizeof(r->error) - 1] = 0;
}

// Semantics bitmask (hpa2_tpu/native.py _sem_flags).  Bit 0 keeps the
// historical 0/1 "robust" encoding valid, so old and new callers stay
// ABI-compatible across a rebuild.
static void apply_sem_flags(Config* cfg, int sem_flags) {
  cfg->nack = (sem_flags & 1) != 0;
  cfg->eager_write_request_memory = (sem_flags & 2) != 0;
  cfg->flush_invack_fills_old_value = (sem_flags & 4) != 0;
  cfg->overloaded_evict_shared_notify = (sem_flags & 8) != 0;
}

// Run a trace directory; writes core_<n>_output.txt into out_dir.
// mode: 0 = lockstep, 1 = omp.  replay_path may be NULL.
// record_order_path (may be NULL/empty): write the executed issue
// interleaving there in DEBUG_INSTR format (assignment.c:596-597).
int hpa2_run_dir(const char* trace_dir, const char* out_dir, int mode,
                 int nodes, int cache, int mem, int cap, int max_instr,
                 int sem_flags, const char* replay_path, int candidates,
                 int final_dump, unsigned long long max_cycles,
                 int threads, const char* record_order_path,
                 const char* msg_trace_path, Hpa2Result* result) {
  Config cfg;
  cfg.nodes = nodes;
  cfg.cache = cache;
  cfg.mem = mem;
  cfg.cap = cap;
  cfg.max_instr = max_instr;
  apply_sem_flags(&cfg, sem_flags);
  std::memset(result, 0, sizeof(*result));
  try {
    auto traces = load_trace_dir(cfg, trace_dir);
    std::vector<IssueRecord> order;
    const std::vector<IssueRecord>* order_p = nullptr;
    if (replay_path && *replay_path) {
      order = load_instruction_order(replay_path);
      order_p = &order;
      mode = 0;
    }
    bool record = record_order_path && *record_order_path;
    bool tmsg = msg_trace_path && *msg_trace_path;
    auto t0 = std::chrono::steady_clock::now();
    RunResult res = (mode == 1)
                        ? run_omp(cfg, traces, threads, record, tmsg)
                        : run_lockstep(cfg, traces, order_p, max_cycles,
                                       candidates != 0, tmsg);
    auto t1 = std::chrono::steady_clock::now();
    result->seconds = std::chrono::duration<double>(t1 - t0).count();
    if (!res.error.empty()) {
      set_err(result, res.error);
      return 1;
    }
    if (record) {
      std::ofstream rf(record_order_path);
      rf << format_instruction_order(res.issue_order);
    }
    if (tmsg) {
      std::ofstream mf(msg_trace_path);
      for (const auto& line : res.msg_log) mf << line << "\n";
    }
    const auto& dumps = final_dump ? res.finals : res.snapshots;
    for (int n = 0; n < cfg.nodes; ++n) {
      std::ofstream f(std::string(out_dir) + "/core_" +
                      std::to_string(n) + "_output.txt");
      f << format_dump(cfg, n, dumps[n]);
      if (candidates) {
        for (size_t k = 0; k < res.candidates[n].size(); ++k) {
          std::ofstream cf(std::string(out_dir) + "/core_" +
                           std::to_string(n) + "_cand_" +
                           std::to_string(k) + ".txt");
          cf << format_dump(cfg, n, res.candidates[n][k]);
        }
      }
    }
    result->instructions = res.counters.instructions;
    result->messages = res.counters.messages;
    result->cycles = res.counters.cycles;
    result->ok = 1;
    return 0;
  } catch (const std::exception& e) {
    set_err(result, e.what());
    return 1;
  }
}

// Synthetic uniform-random benchmark; returns ops/sec via result.
int hpa2_bench_random(int mode, int nodes, int cache, int mem, int cap,
                      int instrs_per_core, unsigned long long seed,
                      int sem_flags, int threads, Hpa2Result* result) {
  Config cfg;
  cfg.nodes = nodes;
  cfg.cache = cache;
  cfg.mem = mem;
  cfg.cap = cap;
  cfg.max_instr = 0;
  apply_sem_flags(&cfg, sem_flags);
  std::memset(result, 0, sizeof(*result));
  try {
    auto traces = gen_uniform_random(cfg, instrs_per_core, seed);
    auto t0 = std::chrono::steady_clock::now();
    RunResult res = (mode == 1)
                        ? run_omp(cfg, traces, threads)
                        : run_lockstep(cfg, traces, nullptr,
                                       1'000'000'000ull, false);
    auto t1 = std::chrono::steady_clock::now();
    result->seconds = std::chrono::duration<double>(t1 - t0).count();
    if (!res.error.empty()) {
      set_err(result, res.error);
      return 1;
    }
    result->instructions = res.counters.instructions;
    result->messages = res.counters.messages;
    result->cycles = res.counters.cycles;
    result->ok = 1;
    return 0;
  } catch (const std::exception& e) {
    set_err(result, e.what());
    return 1;
  }
}

// Single-transition probe for the static-analysis equivalence pass
// (hpa2_tpu/analysis/extract.py).  `probe_in` is the packed 22-slot
// scenario; `probe_out` receives 8 header slots + 5 per emission.
// Returns 0, -1 (bad receiver/index), or -2 (out_cap too small).
int hpa2_probe_transition(int nodes, int cache, int mem, int cap,
                          int sem_flags, const long long* probe_in,
                          long long* probe_out, int out_cap) {
  Config cfg;
  cfg.nodes = nodes;
  cfg.cache = cache;
  cfg.mem = mem;
  cfg.cap = cap;
  apply_sem_flags(&cfg, sem_flags);
  try {
    return probe_transition(cfg, probe_in, probe_out, out_cap);
  } catch (const std::exception&) {
    return -3;
  }
}

}  // extern "C"
