// hpa2sim — native C++/OpenMP backend of the hpa2_tpu framework.
//
// A from-scratch reimplementation of the directory-MESI DSM simulator
// semantics defined by hpa2_tpu/models/spec_engine.py (the executable
// spec; reference behavior at /root/reference/assignment.c:187-697).
// Two execution modes:
//
//  * Lockstep: the deterministic global-cycle engine (handle one
//    message per node -> issue -> deliver in (phase, sender, emission)
//    order -> dump-at-local-completion).  Bit-for-bit equivalent to
//    the Python spec engine and the JAX backend; supports replaying
//    recorded instruction_order.txt interleavings.
//
//  * Free-running OpenMP: thread-per-node like the reference
//    (assignment.c:135-153) but with lock-guarded ring mailboxes,
//    no sleeps, and *global quiescence termination* — the reference
//    never exits (assignment.c:153; SURVEY.md §2.3).  This mode is the
//    ops/sec comparison baseline.
//
// Fixture semantics are the default (SURVEY.md §6.2): no eager memory
// write on WRITE_REQUEST, FLUSH_INVACK installs the requester's
// pending value, and the home->survivor upgrade notification is the
// distinct UPGRADE_NOTIFY type.  The robust intervention policy
// (NACK instead of silently dropping a stale WRITEBACK_*) is
// selectable, as in the other backends.

#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <vector>

namespace hpa2 {

enum class CacheSt : int8_t { M = 0, E = 1, S = 2, I = 3 };
enum class DirSt : int8_t { EM = 0, S = 1, U = 2 };

enum MsgType : int8_t {
  READ_REQUEST = 0,
  WRITE_REQUEST = 1,
  REPLY_RD = 2,
  REPLY_WR = 3,
  REPLY_ID = 4,
  INV = 5,
  UPGRADE = 6,
  WRITEBACK_INV = 7,
  WRITEBACK_INT = 8,
  FLUSH = 9,
  FLUSH_INVACK = 10,
  EVICT_SHARED = 11,
  EVICT_MODIFIED = 12,
  UPGRADE_NOTIFY = 13,  // rebuild extension (fixture semantics)
  NACK = 14,            // rebuild extension (robust mode)
};

struct Config {
  int nodes = 4;
  int cache = 4;
  int mem = 16;
  int cap = 256;        // mailbox capacity (ring size)
  int max_instr = 32;   // 0 = uncapped
  bool nack = false;    // robust intervention policy
  bool eager_write_request_memory = false;  // HEAD quirk
  bool flush_invack_fills_old_value = false;  // HEAD quirk
  // HEAD quirk: the home->survivor "upgrade to E" notification is an
  // overloaded EVICT_SHARED disambiguated only by receiver==home
  // (assignment.c:498-539) instead of the distinct UPGRADE_NOTIFY —
  // faithfully livelocks when the home is itself a sharer
  // (SURVEY.md §6.3).
  bool overloaded_evict_shared_notify = false;

  int num_addresses() const { return nodes * mem; }
  bool parity_format() const {
    return mem == 16 && nodes <= 8 && num_addresses() <= 0xFF;
  }
};

// Sharer sets are a single 64-bit word in the native backend (node
// count <= 64; the Python/JAX backends scale further via multi-word
// masks).
using Sharers = uint64_t;

struct Msg {
  int8_t type;
  int32_t sender;
  int32_t addr;
  int32_t value;
  Sharers sharers;
  int32_t second;
};

struct Instr {
  bool write;
  int32_t addr;
  int32_t value;
};

struct CacheLine {
  int32_t addr = -1;
  int32_t value = 0;
  CacheSt state = CacheSt::I;
};

struct DirEntry {
  DirSt state = DirSt::U;
  Sharers sharers = 0;
};

struct NodeDump {
  std::vector<int32_t> memory;
  std::vector<DirSt> dir_state;
  std::vector<Sharers> dir_sharers;
  std::vector<int32_t> cache_addr;
  std::vector<int32_t> cache_value;
  std::vector<CacheSt> cache_state;
};

struct Counters {
  uint64_t instructions = 0;
  uint64_t messages = 0;
  uint64_t cycles = 0;
};

struct IssueRecord {
  int proc;
  bool write;
  int32_t addr;
  int32_t value;
};

// ---- I/O (byte-exact with the reference formats) --------------------
std::vector<std::vector<Instr>> load_trace_dir(const Config& cfg,
                                               const std::string& dir);
std::vector<IssueRecord> load_instruction_order(const std::string& path);
// DEBUG_INSTR line format (assignment.c:596-597) — inverse of
// load_instruction_order; how the reference's shipped fixture
// interleavings were recorded.
std::string format_instruction_order(const std::vector<IssueRecord>& recs);
std::string format_dump(const Config& cfg, int proc, const NodeDump& d);

// ---- engines --------------------------------------------------------
struct RunResult {
  std::vector<NodeDump> snapshots;               // dump-at-local-completion
  std::vector<NodeDump> finals;                  // quiescent state
  std::vector<std::vector<NodeDump>> candidates; // legal dump timings
  // the executed issue interleaving, in DEBUG_INSTR order — replaying
  // it on a lockstep engine validates a free run and mints new fixture
  // run-sets (the reference's record->replay->verify workflow,
  // SURVEY.md §4)
  std::vector<IssueRecord> issue_order;
  // per-message send/receive log in the reference's DEBUG_MSG format
  // (assignment.c:170-174 receive, 734-738 send); filled when
  // trace_msgs is set
  std::vector<std::string> msg_log;
  Counters counters;
  bool completed = false;   // reached quiescence
  std::string error;
};

RunResult run_lockstep(const Config& cfg,
                       const std::vector<std::vector<Instr>>& traces,
                       const std::vector<IssueRecord>* replay,
                       uint64_t max_cycles,
                       bool capture_candidates,
                       bool trace_msgs = false);

RunResult run_omp(const Config& cfg,
                  const std::vector<std::vector<Instr>>& traces,
                  int num_threads /* 0 = one per node */,
                  bool record_order = false /* fill issue_order; off by
                  default: the per-issue atomic would contend in the
                  benchmark hot loop */,
                  bool trace_msgs = false);

// synthetic workloads for benchmarking (LCG-based, deterministic)
std::vector<std::vector<Instr>> gen_uniform_random(const Config& cfg,
                                                   int instrs_per_core,
                                                   uint64_t seed);

// Single-transition probe for the static-analysis cross-backend
// equivalence pass (hpa2_tpu/analysis/extract.py).  `in` is the packed
// 22-slot scenario; `out` receives 8 header slots + 5 per emission.
// Returns 0, -1 (bad receiver/index), or -2 (out_cap too small).
int probe_transition(const Config& cfg, const long long* in,
                     long long* out, int out_cap);

}  // namespace hpa2
