// hpa2sim CLI — native backend driver.
//
// Usage:
//   hpa2sim [options] TRACE_DIR        run a trace directory
//   hpa2sim [options] --bench INSTRS   synthetic uniform-random bench
//
// Options:
//   --mode lockstep|omp   execution engine (default lockstep)
//   --nodes N --cache C --mem M --cap K --max-instr I
//   --robust              NACK stale interventions (heals livelocks)
//   --head-quirks         reference-HEAD semantics: eager memory write
//                         on WRITE_REQUEST, FLUSH_INVACK installs the
//                         flushed old value, and the overloaded
//                         EVICT_SHARED upgrade-notify (livelocks when
//                         the home is a sharer — SURVEY.md §6.2/§6.3)
//   --quirk NAME          one HEAD quirk: eager-write | flush-old-fill
//                         | overloaded-notify (repeatable)
//   --replay FILE         lockstep replay of an instruction_order.txt
//   --record-order FILE   write the executed issue interleaving in
//                         DEBUG_INSTR format (mints new fixture
//                         run-sets; the record->replay->verify loop)
//   --candidates          also write every legal dump timing per node
//   --final               dump quiescent state instead of
//                         dump-at-local-completion snapshots
//   --out DIR             output directory (default .)
//   --threads T           omp mode thread count (default = nodes)
//   --max-cycles X        lockstep cycle budget
//   --seed S              bench seed
//   --json                print a machine-readable result line
//
// Output files match the reference exactly: core_<n>_output.txt
// (assignment.c:824-875; fixture bitVector rendering).

#include "sim.hpp"

#include <chrono>
#include <cstring>
#include <fstream>
#include <iostream>

using namespace hpa2;

static void write_file(const std::string& path, const std::string& text) {
  std::ofstream f(path);
  if (!f) throw std::runtime_error("cannot write " + path);
  f << text;
}

int main(int argc, char** argv) {
  Config cfg;
  std::string mode = "lockstep";
  std::string trace_dir, replay_path, record_path, msg_trace_path,
      out_dir = ".";
  bool candidates = false, final_dump = false, json = false;
  int bench_instrs = 0, threads = 0;
  uint64_t seed = 0, max_cycles = 100'000'000ull;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        std::cerr << "missing value for " << a << "\n";
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--mode") mode = next();
    else if (a == "--nodes") cfg.nodes = std::stoi(next());
    else if (a == "--cache") cfg.cache = std::stoi(next());
    else if (a == "--mem") cfg.mem = std::stoi(next());
    else if (a == "--cap") cfg.cap = std::stoi(next());
    else if (a == "--max-instr") cfg.max_instr = std::stoi(next());
    else if (a == "--robust") cfg.nack = true;
    else if (a == "--head-quirks") {
      cfg.eager_write_request_memory = true;
      cfg.flush_invack_fills_old_value = true;
      cfg.overloaded_evict_shared_notify = true;
    } else if (a == "--quirk") {
      std::string q = next();
      if (q == "eager-write") cfg.eager_write_request_memory = true;
      else if (q == "flush-old-fill")
        cfg.flush_invack_fills_old_value = true;
      else if (q == "overloaded-notify")
        cfg.overloaded_evict_shared_notify = true;
      else {
        std::cerr << "unknown quirk " << q << "\n";
        return 2;
      }
    }
    else if (a == "--replay") replay_path = next();
    else if (a == "--record-order") record_path = next();
    else if (a == "--trace-msgs") msg_trace_path = next();
    else if (a == "--candidates") candidates = true;
    else if (a == "--final") final_dump = true;
    else if (a == "--out") out_dir = next();
    else if (a == "--threads") threads = std::stoi(next());
    else if (a == "--max-cycles") max_cycles = std::stoull(next());
    else if (a == "--bench") bench_instrs = std::stoi(next());
    else if (a == "--seed") seed = std::stoull(next());
    else if (a == "--json") json = true;
    else if (a.rfind("--", 0) == 0) {
      std::cerr << "unknown option " << a << "\n";
      return 2;
    } else trace_dir = a;
  }

  if (cfg.nodes < 1 || cfg.nodes > 64) {
    std::cerr << "native backend supports 1..64 nodes (use the JAX "
                 "backend beyond)\n";
    return 2;
  }

  try {
    std::vector<std::vector<Instr>> traces;
    if (bench_instrs > 0) {
      cfg.max_instr = 0;
      traces = gen_uniform_random(cfg, bench_instrs, seed);
    } else if (!trace_dir.empty()) {
      traces = load_trace_dir(cfg, trace_dir);
    } else {
      std::cerr << "usage: hpa2sim [options] TRACE_DIR | --bench N\n";
      return 2;
    }

    std::vector<IssueRecord> order;
    const std::vector<IssueRecord>* order_p = nullptr;
    if (!replay_path.empty()) {
      order = load_instruction_order(replay_path);
      order_p = &order;
      mode = "lockstep";
    }

    auto t0 = std::chrono::steady_clock::now();
    RunResult res = (mode == "omp")
                        ? run_omp(cfg, traces, threads,
                                  !record_path.empty(),
                                  !msg_trace_path.empty())
                        : run_lockstep(cfg, traces, order_p, max_cycles,
                                       candidates,
                                       !msg_trace_path.empty());
    auto t1 = std::chrono::steady_clock::now();
    double secs = std::chrono::duration<double>(t1 - t0).count();

    if (!res.error.empty()) {
      std::cerr << "error: " << res.error << "\n";
      return 1;
    }

    if (!record_path.empty())
      write_file(record_path, format_instruction_order(res.issue_order));
    if (!msg_trace_path.empty()) {
      std::string log;
      for (const auto& line : res.msg_log) log += line + "\n";
      write_file(msg_trace_path, log);
    }

    if (bench_instrs == 0) {
      const auto& dumps = final_dump ? res.finals : res.snapshots;
      for (int n = 0; n < cfg.nodes; ++n) {
        write_file(out_dir + "/core_" + std::to_string(n) + "_output.txt",
                   format_dump(cfg, n, dumps[n]));
        if (candidates) {
          for (size_t k = 0; k < res.candidates[n].size(); ++k)
            write_file(out_dir + "/core_" + std::to_string(n) + "_cand_" +
                           std::to_string(k) + ".txt",
                       format_dump(cfg, n, res.candidates[n][k]));
        }
      }
    }

    double ops = res.counters.instructions / (secs > 0 ? secs : 1e-9);
    if (json) {
      std::cout << "{\"mode\":\"" << mode << "\",\"nodes\":" << cfg.nodes
                << ",\"instructions\":" << res.counters.instructions
                << ",\"messages\":" << res.counters.messages
                << ",\"cycles\":" << res.counters.cycles
                << ",\"seconds\":" << secs << ",\"ops_per_sec\":" << ops
                << "}\n";
    } else if (bench_instrs > 0) {
      std::cout << mode << ": " << res.counters.instructions
                << " instrs in " << secs << "s = " << ops << " ops/s\n";
    }
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "error: " << e.what() << "\n";
    return 1;
  }
}
