"""Headline benchmark: simulated RD/WR ops/sec, TPU backends vs the
native OpenMP free-running engine (the reference's execution model,
assignment.c:135-137, rebuilt in native/).

Workload (BASELINE.json configs 3+5): an ensemble of independent
8-node systems, uniform-random RD/WR traces, run to quiescence on one
chip.  Primary engine: the VMEM-resident Pallas kernel
(ops/pallas_engine.py); falls back to the XLA ``lax.while_loop``
engine if the kernel path fails.  Baseline: the C++/OpenMP engine on
the same uniform-random workload shape (both sides report a rate, so
instruction volumes need not match).  Prints ONE JSON line.
"""

from __future__ import annotations

import json
import sys
import time

from hpa2_tpu.config import Semantics, SystemConfig


def bench_pallas(config, batch, instrs_per_core, seed=0):
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    arrays = gen_uniform_random_arrays(config, batch, instrs_per_core,
                                       seed=seed)
    PallasEngine(config, *arrays).run()  # compile + warmup
    eng = PallasEngine(config, *arrays)
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    return eng.instructions, dt


def bench_jax(config, batch, instrs_per_core, seed=0):
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    state = init_state_batched(
        config,
        *gen_uniform_random_arrays(config, batch, instrs_per_core, seed=seed),
    )
    run = build_batched_run(config, max_cycles=1_000_000)

    def once():
        out = run(state)
        return jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)

    once()  # compile warmup
    t0 = time.perf_counter()
    out = once()
    dt = time.perf_counter() - t0
    assert not bool(jnp.any(out.overflow)), "mailbox overflow"
    from hpa2_tpu.ops.step import quiescent

    assert bool(jnp.all(jax.vmap(quiescent)(out))), (
        "batch hit max_cycles before quiescence; throughput would be "
        "measured over a partial workload"
    )
    instrs = int(jnp.sum(out.n_instr))
    return instrs, dt


def bench_omp(config, instrs_per_core, seed=0):
    from hpa2_tpu import native

    res = native.bench_random(
        config, instrs_per_core=instrs_per_core, seed=seed, mode="omp"
    )
    return int(res.instructions), float(res.seconds)


def main():
    config = SystemConfig(
        num_procs=8, msg_buffer_size=32, semantics=Semantics().robust()
    )
    import jax

    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    if on_tpu:
        batch, instrs_per_core = 8192, 128  # 8.4M instrs
    else:  # CPU smoke (pallas runs interpreted): keep it tiny
        batch, instrs_per_core = 8, 16

    engine = "pallas"
    try:
        jax_instrs, jax_dt = bench_pallas(config, batch, instrs_per_core)
    except Exception as e:
        print(f"pallas path failed ({e}); falling back to XLA engine",
              file=sys.stderr)
        engine = "xla"
        if on_tpu:
            batch = 1024
        jax_instrs, jax_dt = bench_jax(config, batch, instrs_per_core)
    jax_ops = jax_instrs / jax_dt

    try:
        omp_instrs, omp_dt = bench_omp(config, instrs_per_core=50_000)
        omp_ops = omp_instrs / omp_dt
    except Exception as e:  # baseline unavailable: report jax-only
        print(json.dumps({
            "metric": "sim_ops_per_sec_jax",
            "value": round(jax_ops, 1),
            "unit": "RD/WR ops/sec",
            "vs_baseline": None,
            "note": f"omp baseline failed: {e}",
        }))
        return 0

    print(json.dumps({
        "metric": "sim_ops_per_sec_jax",
        "value": round(jax_ops, 1),
        "unit": "RD/WR ops/sec",
        "vs_baseline": round(jax_ops / omp_ops, 2),
        "engine": engine,
        "jax_instrs": jax_instrs,
        "jax_seconds": round(jax_dt, 4),
        "omp_ops_per_sec": round(omp_ops, 1),
        "omp_instrs": omp_instrs,
        "omp_seconds": round(omp_dt, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
