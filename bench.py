"""Headline benchmark: simulated RD/WR ops/sec, TPU backends vs the
native OpenMP free-running engine (the reference's execution model,
assignment.c:135-137, rebuilt in native/).

Workload (BASELINE.json configs 3+5): an ensemble of independent
8-node systems, uniform-random RD/WR traces, run to quiescence on one
chip.  Primary engine: the VMEM-resident Mosaic Pallas kernel
(ops/pallas_engine.py) driven by its on-device run loop; falls back to
the XLA ``lax.while_loop`` engine if the kernel path fails — and says
so in the JSON (``engine`` + ``pallas_error``).  Baseline: the
C++/OpenMP engine on the same uniform-random workload shape (both
sides report a rate, so instruction volumes need not match).

ALWAYS prints exactly ONE JSON line on stdout.  The axon TPU tunnel
can hang or refuse backend init (round-1 artifact: rc=1, no JSON; the
round-4 tunnel also wedged mid-session), so the parent process never
touches JAX itself: it probes the TPU in a timeout-guarded subprocess
(one retry), PROBE-COMPILES the Pallas kernel in a second subprocess
(the cheap Mosaic smoke gate the round-3 verdict asked for — a
regression fails loudly here, not 540s into a bench), runs the
measurement in a third, and if every child fails it still emits a
JSON line with a ``note``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
# escalating probe budget: the axon tunnel's cold start has been seen
# to need minutes (the dryrun budget is 480s); 90s x2 was too brittle
# (BENCH_r04: both probes timed out while the same-day dryrun passed).
# When a last-good TPU record exists the ladder is shorter — a
# tunnel-down round then reports the dated stale number instead of
# gambling the caller's whole time budget on a third long probe.
_PROBE_TIMEOUTS_S = (90, 180, 480)
_PROBE_TIMEOUTS_WITH_FALLBACK_S = (90, 240)
_COMPILE_GATE_TIMEOUT_S = 240
_TPU_CHILD_TIMEOUT_S = 540
_CPU_CHILD_TIMEOUT_S = 300
# every successful TPU measurement is persisted here so a tunnel-down
# round still reports the last real TPU number (marked stale)
_LAST_TPU_PATH = os.path.join(_REPO_ROOT, "BENCH_LAST_TPU.json")

# bench workload shape (see child_main)
_TPU_BATCH, _TPU_INSTRS = 32768, 128
_BLOCK, _CAP, _WINDOW, _K = 512, 16, 32, 128
_GATE = True
# measurement sessions (scripts/r5_tpu_session.py) write the best
# swept kernel shape here so the next bench run uses it without a
# code edit; absent/invalid -> the defaults above
_TUNING_PATH = os.path.join(_REPO_ROOT, "BENCH_TUNING.json")


def _tuned_shape():
    block, window, k, gate = _BLOCK, _WINDOW, _K, _GATE
    try:
        with open(_TUNING_PATH) as f:
            t = json.load(f)
        block = int(t.get("block", block))
        window = int(t.get("window", window))
        k = int(t.get("k", k))
        gate = bool(t.get("gate", gate))
    except Exception:  # noqa: BLE001 - ANY malformed tuning file must
        # degrade to the known-good defaults, never crash the bench
        return _BLOCK, _WINDOW, _K, _GATE
    return block, window, k, gate


def _bench_config():
    from hpa2_tpu.config import Semantics, SystemConfig

    return SystemConfig(
        num_procs=8, msg_buffer_size=_CAP,
        semantics=Semantics().robust(),
        elide=not _no_elide(),
        exchange_mode=_exchange_mode(),
    )


def _data_shards() -> int:
    """Ensemble data-parallelism degree (``--data-shards``), carried
    to the measurement children through the environment (the child
    argv protocol is positional)."""
    try:
        return max(1, int(os.environ.get("HPA2_BENCH_DATA_SHARDS", "1")))
    except ValueError:
        return 1


def _node_shards() -> int:
    """Node-axis sharding degree (``--node-shards``): splits every
    simulated system's node planes over that many devices, with
    cross-shard delivery by the targeted ppermute exchange.  Composes
    with ``--data-shards`` into a 2-D ``data x node`` mesh."""
    try:
        return max(1, int(os.environ.get("HPA2_BENCH_NODE_SHARDS", "1")))
    except ValueError:
        return 1


def _exchange_mode() -> str:
    """Cross-shard transport schedule (``--exchange-mode``): one of
    ``ops/exchange.EXCHANGE_MODES``; only observable at
    ``--node-shards`` > 1 (single-shard runs have no exchange)."""
    return (
        os.environ.get("HPA2_BENCH_EXCHANGE_MODE", "").strip() or "a2a"
    )


def _packed() -> bool:
    """Packed-state-plane knob (``--packed``): run the Pallas engines
    with the uint8/uint16 split planes instead of int32 words."""
    return os.environ.get("HPA2_BENCH_PACKED", "") == "1"


def _no_elide() -> bool:
    """Cycle-elision A/B knob (``--no-elide``): rebuild the XLA run
    programs as pure lockstep (``Config.elide=False``) so elided vs
    lockstep wall-clock lands in artifact diffs.  The Pallas engines
    run lockstep either way (their in-kernel quiescence gate already
    skips drained blocks), so this only moves the XLA paths."""
    return os.environ.get("HPA2_BENCH_NO_ELIDE", "") == "1"


def _schedule_knobs():
    """Occupancy-scheduler knobs: ``--schedule-resident N`` turns the
    scheduler on (0 = off), ``--host-barriers`` selects the PR-5
    one-launch-per-interval loop instead of the fused single-program
    default.  Returns (resident, fused)."""
    try:
        resident = int(
            os.environ.get("HPA2_BENCH_SCHEDULE_RESIDENT", "0")
        )
    except ValueError:
        resident = 0
    fused = os.environ.get("HPA2_BENCH_HOST_BARRIERS", "") != "1"
    return max(0, resident), fused


def _trace_len_dist():
    """Heterogeneous-workload knob (``--trace-len-dist``): returns
    (dist, spread) or (None, spread) for the default homogeneous
    uniform-random traces.  Carried to the children through the
    environment, like ``--data-shards``."""
    dist = os.environ.get("HPA2_BENCH_TRACE_DIST", "").strip() or None
    try:
        spread = float(os.environ.get("HPA2_BENCH_TRACE_SPREAD", "8"))
    except ValueError:
        spread = 8.0
    return dist, max(1.0, spread)


# ---------------------------------------------------------------------------
# children (each runs in its own interpreter under a known-good env)
# ---------------------------------------------------------------------------

def compile_gate_main() -> int:
    """Compile-only AOT lowering of the HBM-streaming whole-run
    program (no execution): catches Mosaic regressions in seconds and
    reports the compiler-measured VMEM next to the static budget
    model's prediction.  Prints one JSON line."""
    import jax

    from hpa2_tpu.analysis.vmem import measured_vmem_bytes, vmem_budget
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = _bench_config()
    block, window, _, gate = _tuned_shape()
    arrays = gen_uniform_random_arrays(config, max(block, 1024),
                                       2 * window, seed=0)
    bud = vmem_budget(config, block, window, snapshots=False,
                      gate=gate, stream=True)
    t0 = time.time()
    try:
        eng = PallasEngine(config, *arrays, block=block,
                           cycles_per_call=8, interpret=False,
                           snapshots=False, trace_window=window,
                           gate=gate)
        compiled = eng.lower_run(max_cycles=10_000).compile()
    except Exception as e:  # noqa: BLE001 - reported upward as data
        print(json.dumps({"ok": False, "error": str(e)[-400:],
                          "model_vmem_bytes": bud.total_bytes}))
        return 1
    print(json.dumps({"ok": True, "compile_s": round(time.time() - t0, 1),
                      "platform": jax.devices()[0].platform,
                      "model_vmem_bytes": bud.total_bytes,
                      "measured_vmem_bytes": measured_vmem_bytes(compiled)}))
    return 0


def bench_pallas(config, batch, instrs_per_core, seed=0, data_shards=1,
                 node_shards=1, dist=None, spread=8.0, packed=False,
                 schedule_resident=0, fused=True):
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import (gen_heterogeneous_random_arrays,
                                      gen_uniform_random_arrays)

    block, window, k, gate = _tuned_shape()
    schedule = None
    if schedule_resident:
        from hpa2_tpu.ops.schedule import Schedule

        schedule = Schedule(
            resident=min(schedule_resident, batch), fused=fused
        )
    occupancy = None
    if dist:
        arrays = gen_heterogeneous_random_arrays(
            config, batch, instrs_per_core, dist=dist, spread=spread,
            seed=seed)
        # static occupancy model over the SAME lengths the generator
        # drew (shared helper, same seed): mean live-lane fraction and
        # block-segments vs the lockstep bound at the tuned kernel
        # shape.  The model replays the engines' exact barrier policy
        # (see hpa2_tpu/analysis/occupancy.py), so this is what
        # ``schedule=`` would save — recorded in the artifact without
        # perturbing the measured run.
        from hpa2_tpu.analysis.occupancy import predicted_stats
        from hpa2_tpu.ops.pallas_engine import choose_block
        from hpa2_tpu.utils.trace import heterogeneous_lengths

        lens = heterogeneous_lengths(batch, instrs_per_core,
                                     dist=dist, spread=spread, seed=seed)
        occupancy = predicted_stats(
            lens, window, choose_block(batch // data_shards, block),
            groups=data_shards,
        ).as_dict()
    else:
        arrays = gen_uniform_random_arrays(config, batch,
                                           instrs_per_core, seed=seed)

    extra = dict(packed=packed)
    if schedule is not None:
        extra["schedule"] = schedule
    if node_shards > 1:
        from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine

        def build():
            return NodeShardedPallasEngine(
                config, *arrays, node_shards=node_shards,
                data_shards=data_shards, block=block,
                cycles_per_call=k, snapshots=False,
                trace_window=window, gate=gate, **extra)
    elif data_shards > 1:
        from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

        def build():
            return DataShardedPallasEngine(
                config, *arrays, data_shards=data_shards, block=block,
                cycles_per_call=k, snapshots=False,
                trace_window=window, gate=gate, **extra)
    else:

        def build():
            return PallasEngine(config, *arrays, block=block,
                                cycles_per_call=k, snapshots=False,
                                trace_window=window, gate=gate, **extra)

    build().run()  # compile + warmup
    # measured run, phase-split: host staging (trace gen is done above;
    # this is packing + device_put of the ensemble planes), device
    # execution, and the counter readback sync
    t0 = time.perf_counter()
    eng = build()
    stage_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    instrs = eng.instructions
    read_s = time.perf_counter() - t0
    phases = {
        "host_staging_s": round(stage_s, 4),
        "device_execute_s": round(dt, 4),
        "readback_s": round(read_s, 4),
    }
    exchange = None
    if node_shards > 1:
        from hpa2_tpu.ops import exchange as xops

        xmsgs = eng.cross_shard_msgs
        cycles = max(eng.cycle, 1)
        plan = xops.make_plan(
            node_shards, config.exchange_mode, config.exchange_inner
        )
        stats = eng.stats()
        exchange = {
            "node_shards": node_shards,
            "exchange_mode": config.exchange_mode,
            "collectives_per_cycle": xops.plan_collectives(plan),
            "exchange_slots": 5 * (config.num_procs // node_shards),
            "cross_shard_msgs": xmsgs,
            "cross_shard_msgs_per_cycle": round(xmsgs / cycles, 2),
            "exchange_slot_hwm": stats.get("exchange_slot_hwm", 0),
            "exchange_bytes_per_cycle": stats.get(
                "exchange_bytes_per_cycle", 0
            ),
            "exchange_multicast_saved": stats.get(
                "exchange_multicast_saved", 0
            ),
            "exchange_combined": stats.get("exchange_combined", 0),
            "msgs_total": eng.messages,
        }
    if schedule is not None:
        # a scheduled run reports ITS occupancy counters — on the
        # fused path they flow from the plan/replay model (the host
        # loop that used to measure them no longer exists), on the
        # PR-5 path from the loop itself; the work counters are
        # bit-identical either way, only the launch accounting
        # (host_barriers/device_programs) differs
        occupancy = eng.occupancy.as_dict()
    return instrs, dt, occupancy, exchange, phases


def bench_jax(config, batch, instrs_per_core, seed=0):
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.ops.step import quiescent
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    state = init_state_batched(
        config,
        *gen_uniform_random_arrays(config, batch, instrs_per_core, seed=seed),
    )
    run = build_batched_run(config, max_cycles=1_000_000)

    def once():
        out = run(state)
        return jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)

    once()  # compile warmup
    t0 = time.perf_counter()
    out = once()
    dt = time.perf_counter() - t0
    assert not bool(jnp.any(out.overflow)), "mailbox overflow"
    assert bool(jnp.all(jax.vmap(quiescent)(out))), (
        "batch hit max_cycles before quiescence; throughput would be "
        "measured over a partial workload"
    )
    instrs = int(jnp.sum(out.n_instr))
    # elision counters (only-when-nonzero, like the stats schema):
    # zero under --no-elide and whenever the workload never had a
    # provably-quiet cycle to skip
    counters = {}
    for key, field in (("elided_cycles", out.n_elided),
                       ("multi_hit_retired", out.n_multi_hit)):
        val = int(jnp.sum(field))
        if val:
            counters[key] = val
    return instrs, dt, counters


def bench_omp(config, instrs_per_core, seed=0, mode="omp"):
    from hpa2_tpu import native

    res = native.bench_random(
        config, instrs_per_core=instrs_per_core, seed=seed, mode=mode
    )
    return int(res.instructions), float(res.seconds)


def child_main(platform: str, pallas_ok: bool, pallas_error: str) -> int:
    config = _bench_config()
    on_tpu = platform == "tpu"
    shards = _data_shards()
    node_shards = _node_shards()
    if on_tpu:
        batch, instrs_per_core = _TPU_BATCH, _TPU_INSTRS  # 33.5M instrs
    else:  # CPU smoke (pallas runs interpreted): keep it tiny
        batch, instrs_per_core = 8, 16
    if batch % shards:  # the ensemble splits into equal lane groups
        batch = -(-batch // shards) * shards

    dist, spread = _trace_len_dist()
    packed = _packed()
    resident, fused = _schedule_knobs()
    engine = "pallas"
    err = pallas_error
    ran_ok = False
    occupancy = None
    exchange = None
    phases = None
    elision = {}
    if pallas_ok or not on_tpu:  # CPU always tries interpret mode
        try:
            jax_instrs, jax_dt, occupancy, exchange, phases = bench_pallas(
                config, batch, instrs_per_core, data_shards=shards,
                node_shards=node_shards, dist=dist, spread=spread,
                packed=packed, schedule_resident=resident, fused=fused)
            ran_ok = True
        except Exception as e:  # noqa: BLE001
            err = str(e)[-300:]
    if not ran_ok:
        print(f"pallas path failed ({err}); falling back to XLA engine",
              file=sys.stderr)
        engine = "xla"
        if on_tpu:
            batch = 1024
        jax_instrs, jax_dt, elision = bench_jax(
            config, batch, instrs_per_core
        )
    jax_ops = jax_instrs / jax_dt

    result = {
        "metric": "sim_ops_per_sec_jax",
        "value": round(jax_ops, 1),
        "unit": "RD/WR ops/sec",
        "vs_baseline": None,
        "engine": engine,
        "platform": platform,
        # the CPU smoke shape (batch 8, interpret mode) measures
        # nothing representative — its ops/sec is NOT the headline
        "indicative": on_tpu,
        "batch": batch,
        "jax_instrs": jax_instrs,
        "jax_seconds": round(jax_dt, 4),
    }
    # kernel-layout / scheduler provenance: always recorded so artifact
    # diffs across rounds show WHICH path produced the number
    result["packed_planes"] = packed and engine == "pallas"
    # event-driven elision provenance (+ counters when it fired; the
    # lockstep Pallas engines always report none)
    result["elide"] = config.elide
    result.update(elision)
    result["fused_schedule"] = bool(
        resident and fused and engine == "pallas"
    )
    if resident and engine == "pallas":
        result["schedule"] = {"resident": resident, "fused": fused}
    if dist:
        result["trace_len_dist"] = {"dist": dist, "spread": spread}
    if occupancy is not None:
        result["occupancy"] = occupancy
    if phases is not None:
        result["phases"] = phases
    if shards != 1:
        import jax

        result["data_shards"] = shards
        result["n_devices"] = len(jax.devices())
        if engine != "pallas":
            result["data_shards_note"] = "xla fallback ran unsharded"
    if node_shards != 1:
        import jax

        result["node_shards"] = node_shards
        result["n_devices"] = len(jax.devices())
        if exchange is not None:
            result["cross_shard_msgs_per_cycle"] = exchange[
                "cross_shard_msgs_per_cycle"]
            result["exchange"] = exchange
            print(
                f"[pallas] cross-shard msgs: "
                f"{exchange['cross_shard_msgs']} "
                f"({exchange['cross_shard_msgs_per_cycle']}/cycle)",
                file=sys.stderr,
            )
        if engine != "pallas":
            result["node_shards_note"] = "xla fallback ran unsharded"
    if engine != "pallas":
        result["pallas_error"] = err
    else:
        block, window, k, gate = _tuned_shape()
        result["kernel_shape"] = {
            "block": block, "window": window, "k": k, "gate": gate,
        }
    if on_tpu:
        # the host-sensitive OpenMP ratio only means something at the
        # real TPU workload shape; the CPU smoke ratio (0.22x at
        # batch 8 / interpret mode) was noise dressed as a headline
        try:
            omp_instrs, omp_dt = bench_omp(config,
                                           instrs_per_core=50_000)
            omp_ops = omp_instrs / omp_dt
            result.update(
                vs_baseline=round(jax_ops / omp_ops, 2),
                omp_ops_per_sec=round(omp_ops, 1),
                omp_instrs=omp_instrs,
                omp_seconds=round(omp_dt, 4),
            )
        except Exception as e:  # baseline unavailable: report jax-only
            result["note"] = f"omp baseline failed: {e}"
    try:
        # context: the deterministic single-threaded native engine —
        # on small hosts it beats thread-per-node by an order of
        # magnitude (lock thrash under oversubscription), so the
        # free-running baseline's host sensitivity is visible in the
        # artifact
        ls_instrs, ls_dt = bench_omp(
            config, instrs_per_core=50_000, mode="lockstep"
        )
        result["native_lockstep_ops_per_sec"] = round(
            ls_instrs / ls_dt, 1
        )
    except Exception as e:  # optional context only — never fatal
        result["native_lockstep_note"] = f"lockstep context failed: {e}"
    try:
        # link-layer observability (ISSUE 11 satellite): a small faulty
        # ensemble surfaces the retransmission/delay counters in the
        # artifact, so fault-injection coverage is visible per round
        # (deterministic: seeded fault stream, fixed traces)
        import dataclasses as _dc

        from hpa2_tpu.config import FaultModel
        from hpa2_tpu.ops.engine import BatchJaxEngine
        from hpa2_tpu.utils.trace import gen_uniform_random

        fcfg = _dc.replace(
            config,
            interconnect=_dc.replace(
                config.interconnect,
                fault=FaultModel(drop=0.2, duplicate=0.05,
                                 reorder=0.05, delay=0.1, seed=7),
            ),
        )
        fbe = BatchJaxEngine(
            fcfg, [gen_uniform_random(fcfg, 16, seed=s) for s in range(4)]
        ).run()
        result["fault_counters"] = {
            k: v for k, v in fbe.stats().items()
            if k.startswith("fault_")
        }
    except Exception as e:  # optional context only — never fatal
        result["fault_counters_note"] = f"faulty context failed: {e}"
    print(json.dumps(result))
    return 0


def _serve_knobs(on_tpu: bool):
    """Serving-bench geometry, overridable via HPA2_SERVE_* env vars
    (the measurement session's serve512 step scales these up without a
    code edit)."""

    def _int(name, default):
        try:
            return int(os.environ.get(name, str(default)))
        except ValueError:
            return default

    resident = _int("HPA2_SERVE_RESIDENT", 4096 if on_tpu else 8)
    jobs_n = _int("HPA2_SERVE_JOBS", 4 * resident)
    instrs = _int("HPA2_SERVE_INSTRS", 128 if on_tpu else 24)
    window = _int("HPA2_SERVE_WINDOW", _tuned_shape()[1] if on_tpu else 8)
    block = _int("HPA2_SERVE_BLOCK", _tuned_shape()[0] if on_tpu else 8)
    policy = os.environ.get("HPA2_SERVE_POLICY", "fcfs")
    backend = os.environ.get("HPA2_SERVE_BACKEND", "pallas")
    return resident, jobs_n, instrs, window, block, policy, backend


def serve_child_main(platform: str) -> int:
    """The always-on serving benchmark (one JSON line):

    1. capacity, pipelined: the whole feed released at once with
       overlapped host-device staging -> sustained ops/sec + phase
       split,
    2. capacity, serial: same feed with ``overlap=False`` -> the
       staging time the pipeline hides (``hidden_s``),
    3. Poisson arrivals at ~60% of measured capacity -> p50/p99 job
       latency under steady load,
    4. heavy-tail zipf bursts at the same mean rate -> the tail under
       overload bursts.
    """
    from hpa2_tpu.serving import (
        ListJobSource, poisson_arrivals, serve, synthetic_jobs,
        zipf_burst_arrivals)

    config = _bench_config()
    on_tpu = platform == "tpu"
    (resident, jobs_n, instrs, window, block, policy,
     backend) = _serve_knobs(on_tpu)
    data_shards = _data_shards()
    if backend == "pallas" and data_shards > 1:
        backend = "pallas-sharded"

    def _serve(jobs, *, overlap, timed=False):
        return serve(
            config, ListJobSource(jobs, timed=timed), backend=backend,
            resident=resident, window=window, block=block,
            policy=policy, data_shards=data_shards, overlap=overlap,
            max_trace_len=instrs, decode_dumps=False,
        )

    jobs = synthetic_jobs(config, jobs_n, instrs, seed=0, dist="zipf",
                          spread=4.0)
    # warmup: populate the jit caches so the measured runs compare
    # steady-state staging, not compile time
    _serve(synthetic_jobs(config, min(jobs_n, 2 * resident), instrs,
                          seed=99, dist="zipf", spread=4.0),
           overlap=True)

    _, pipelined = _serve(jobs, overlap=True)
    _, serial = _serve(jobs, overlap=False)
    hidden_s = max(0.0, serial.wall_s - pipelined.wall_s)
    overlap_cmp = {
        "pipelined_wall_s": round(pipelined.wall_s, 4),
        "serial_wall_s": round(serial.wall_s, 4),
        "hidden_s": round(hidden_s, 4),
        # what fraction of the serial run's host staging the pipeline
        # hid behind device execution
        "staging_hidden_frac": round(
            min(1.0, hidden_s / serial.host_staging_s), 3
        ) if serial.host_staging_s > 0 else 0.0,
    }

    # arrival-process runs at ~60% of the measured capacity
    capacity = max(pipelined.jobs_completed / pipelined.wall_s, 1e-9)
    rate = 0.6 * capacity
    arr_runs = {}
    for name, arrivals in (
        ("poisson", poisson_arrivals(jobs_n, rate, seed=1)),
        ("zipf_burst", zipf_burst_arrivals(jobs_n, rate, seed=1)),
    ):
        feed = synthetic_jobs(config, jobs_n, instrs, seed=2,
                              dist="zipf", spread=4.0,
                              arrivals=arrivals)
        _, st = _serve(feed, overlap=True, timed=True)
        rec = st.as_dict()
        rec["arrival_rate_jobs_per_s"] = round(rate, 2)
        arr_runs[name] = rec

    # multi-tenant pass: 4 weighted tenants, a deadline mix, fair-drr
    # admission -> per-tenant latency percentiles + deadline hit rate
    import numpy as np

    weights = {"t0": 1.0, "t1": 2.0, "t2": 4.0, "t3": 8.0}
    names = sorted(weights)
    mt_jobs = synthetic_jobs(config, jobs_n, instrs, seed=3,
                             dist="zipf", spread=4.0)
    for i, j in enumerate(mt_jobs):
        j.tenant = names[i % len(names)]
        j.deadline = (8, 32, -1)[i % 3]
    mt_res, mt_st = serve(
        config, ListJobSource(mt_jobs), backend=backend,
        resident=resident, window=window, block=block,
        policy="fair-drr", data_shards=data_shards, overlap=True,
        max_trace_len=instrs, decode_dumps=False,
        tenant_weights=weights,
    )
    per_tenant = {}
    for name in names:
        lat = np.asarray(
            [r.latency_s for r in mt_res if r.tenant == name])
        if len(lat):
            per_tenant[name] = {
                "jobs": int(len(lat)),
                "p50_s": round(float(np.percentile(lat, 50)), 6),
                "p99_s": round(float(np.percentile(lat, 99)), 6),
            }
    mt_occ = mt_st.occupancy
    multi_tenant = {
        "policy": mt_st.policy,
        "tenant_weights": weights,
        "deadline_met": mt_occ.get("deadline_met", 0),
        "deadline_missed": mt_occ.get("deadline_missed", 0),
        "deadline_hit_rate": mt_occ.get("deadline_hit_rate"),
        "tenant_share": mt_occ.get("tenant_share"),
        "per_tenant_latency_s": per_tenant,
    }

    result = {
        "metric": "serving_sustained_ops_per_sec",
        "value": round(pipelined.ops_per_s, 1),
        "unit": "RD/WR ops/sec",
        "platform": platform,
        # the CPU smoke shape measures nothing representative
        "indicative": on_tpu,
        "backend": backend,
        "resident": resident,
        "jobs": jobs_n,
        "instrs_per_core": instrs,
        "window": window,
        "block": block,
        # the *active* policy/elision of the measured runs, read back
        # from the serving stats and config rather than the env knobs
        "policy": pipelined.policy,
        "elide": config.elide,
        "data_shards": data_shards,
        "overlap": overlap_cmp,
        "capacity_pipelined": pipelined.as_dict(),
        "capacity_serial": serial.as_dict(),
        "arrivals": arr_runs,
        "multi_tenant": multi_tenant,
    }
    print(json.dumps(result))
    return 0


def failover_child_main(platform: str) -> int:
    """``bench.py --failover`` child: recovery-latency numbers for the
    fault-tolerance supervisor (one JSON line):

    1. unfailed baseline serve -> wall time + dump transcript,
    2. one supervised run per failure kind (kill / hang / poison at
       the same interval barrier) -> recovery overhead vs baseline,
       recovery counters, and the byte-identity check against the
       unfailed dumps,
    3. a mid-frame wire sever against a live framed server -> the
       client-observed blackout (disconnect + backoff + reconnect +
       session resume) and the idempotent-resubmit check.
    """
    import tempfile
    import threading

    from hpa2_tpu.config import FailurePlan
    from hpa2_tpu.service import WireClient, WireJobSource
    from hpa2_tpu.serving import (
        ListJobSource, job_to_record, serve, supervised_serve,
        synthetic_jobs)

    config = _bench_config()
    on_tpu = platform == "tpu"
    (resident, jobs_n, instrs, window, block, policy,
     backend) = _serve_knobs(on_tpu)
    try:
        fail_at = int(os.environ.get("HPA2_FAILOVER_AT", "3"))
    except ValueError:
        fail_at = 3

    kw = dict(backend=backend, resident=resident, window=window,
              block=block, policy=policy, max_trace_len=instrs,
              decode_dumps=False)
    jobs = synthetic_jobs(config, jobs_n, instrs, seed=0, dist="zipf",
                          spread=4.0)

    def _dump_map(res):
        return {r.job_id: tuple(repr(d) for d in r.dumps)
                for r in res}

    # warmup the jit caches, then the unfailed baseline
    serve(config,
          ListJobSource(synthetic_jobs(
              config, min(jobs_n, 2 * resident), instrs, seed=99,
              dist="zipf", spread=4.0)), **kw)
    t0 = time.perf_counter()
    base_res, _ = serve(config, ListJobSource(jobs), **kw)
    base_wall = time.perf_counter() - t0
    want = _dump_map(base_res)

    runs = {}
    for kind, spec in (("kill", f"kill@{fail_at}"),
                       ("hang", f"hang@{fail_at}"),
                       ("poison", f"poison@{fail_at}:1")):
        plan = FailurePlan.parse(spec, seed=11)
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            res, st = supervised_serve(
                config, ListJobSource(jobs), plan=plan,
                checkpoint_dir=td, **kw)
            wall = time.perf_counter() - t0
        rec = dict(st.occupancy.get("recovery", {}))
        rec.pop("events", None)
        runs[kind] = {
            "wall_s": round(wall, 4),
            "recovery_overhead_s": round(max(0.0, wall - base_wall), 4),
            "byte_identical": _dump_map(res) == want,
            **rec,
        }

    # wire-layer blackout: sever the connection mid-ACK at seq 2, let
    # the client ride retry/backoff + session resume back in
    sever_plan = FailurePlan.parse("sever@2", seed=7)
    src = WireJobSource(config, failures=sever_plan)
    recs = [job_to_record(j) for j in jobs[:min(8, len(jobs))]]
    blackout = {}

    def client():
        cli = WireClient(*src.address, timeout_s=30.0, retries=4,
                         backoff_s=0.02, backoff_seed=11)
        worst = 0.0
        for r in recs:
            t0 = time.perf_counter()
            cli.submit(r)
            worst = max(worst, time.perf_counter() - t0)
        cli.finish()
        blackout["blackout_s"] = round(worst, 4)
        blackout["client_retries"] = cli.retries
        blackout["session"] = cli.session
        cli.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    serve(config, src, emit=src.deliver, **kw)
    t.join(timeout=120)

    result = {
        "metric": "failover_recovery_overhead_s",
        "value": runs["kill"]["recovery_overhead_s"],
        "unit": "seconds",
        "platform": platform,
        "indicative": on_tpu,
        "backend": backend,
        "resident": resident,
        "jobs": jobs_n,
        "instrs_per_core": instrs,
        "fail_at_interval": fail_at,
        "baseline_wall_s": round(base_wall, 4),
        "runs": runs,
        "wire_sever": blackout,
    }
    print(json.dumps(result))
    return 0


# ---------------------------------------------------------------------------
# parent: platform probe + subprocess orchestration, always one JSON line
# ---------------------------------------------------------------------------

def _hostenv():
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    from hpa2_tpu import hostenv

    return hostenv


def _probe_tpu() -> bool:
    """True iff a fresh interpreter sees a TPU within the timeout.
    Retries escalate the budget (tunnel cold starts have needed
    minutes); rc=3 ("no TPU present") is a deterministic answer, not
    tunnel flakiness, and stops the retries."""
    code = (
        "import sys, jax; ds = jax.devices(); "
        "sys.exit(0 if any('tpu' in str(d).lower() for d in ds) else 3)"
    )
    ladder = (
        _PROBE_TIMEOUTS_WITH_FALLBACK_S
        if _load_last_tpu() is not None
        else _PROBE_TIMEOUTS_S
    )
    for attempt, budget in enumerate(ladder):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                env=_hostenv().cache_env(dict(os.environ)),
                cwd=_REPO_ROOT,
                timeout=budget,
                capture_output=True,
            )
            if proc.returncode == 0:
                return True
            print(
                f"tpu probe attempt {attempt + 1}: rc={proc.returncode} "
                f"{proc.stderr.decode(errors='replace')[-200:]!r}",
                file=sys.stderr,
            )
            if proc.returncode == 3:
                return False
        except subprocess.TimeoutExpired:
            print(
                f"tpu probe attempt {attempt + 1}: timeout ({budget}s)",
                file=sys.stderr,
            )
    return False


def _record_last_tpu(result: dict) -> None:
    """Persist a successful TPU measurement (committed to the repo so
    a tunnel-down round still carries the last real number).  An
    XLA-fallback run never overwrites a pallas record — the fallback
    is ~an order of magnitude slower, and replacing the real number
    with it would make the next tunnel-down round read as a perf
    regression."""
    try:
        prev = _load_last_tpu()
        if (
            prev is not None
            and prev.get("engine") == "pallas"
            and result.get("engine") != "pallas"
        ):
            return
        rec = dict(result)
        rec["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        with open(_LAST_TPU_PATH, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except OSError as e:
        print(f"could not persist last-good TPU result: {e}",
              file=sys.stderr)


def _load_last_tpu():
    try:
        with open(_LAST_TPU_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _compile_gate():
    """Run the Mosaic compile smoke child; returns (ok, error_str)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--compile-gate"],
            env=_hostenv().cache_env(dict(os.environ)),
            cwd=_REPO_ROOT,
            timeout=_COMPILE_GATE_TIMEOUT_S,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"compile gate timeout ({_COMPILE_GATE_TIMEOUT_S}s)"
    sys.stderr.write(
        _filter_xla_spew(proc.stderr.decode(errors="replace"))[-2000:])
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            return bool(rec.get("ok")), rec.get("error", "")
    return False, f"compile gate rc={proc.returncode}, no JSON"


def _filter_xla_spew(text: str) -> str:
    """Drop XLA's host-CPU-feature-mismatch warning (a multi-KB dump
    of +avx512.../-amx... flags ending in "...such as SIGILL") from a
    child's relayed stderr.  It fires on every CPU smoke run, carries
    no signal for this workload, and used to dominate the BENCH_*.json
    ``tail`` the artifact driver captures — burying the one JSON line
    the tail exists to show."""
    markers = ("host machine features", "cpu_feature_guard",
               "errors such as SIGILL")
    kept = [ln for ln in text.splitlines()
            if not any(m in ln for m in markers)]
    return "\n".join(kept) + ("\n" if kept else "")


def _child_env(platform: str):
    hostenv = _hostenv()
    # the (data, node) mesh needs data_shards * node_shards devices
    shards = _data_shards() * _node_shards()
    return (
        hostenv.cache_env(dict(os.environ))
        if platform == "tpu"
        # a sharded CPU smoke needs that many virtual devices
        else hostenv.forced_cpu_env(
            n_devices=shards if shards > 1 else None
        )
    )


def _run_child(platform: str, timeout_s: int, pallas_ok: bool,
               pallas_error: str):
    """Run the measurement child; returns the parsed JSON dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", platform,
             "1" if pallas_ok else "0", pallas_error],
            env=_child_env(platform),
            cwd=_REPO_ROOT,
            timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print(f"{platform} bench child: timeout ({timeout_s}s)",
              file=sys.stderr)
        return None
    sys.stderr.write(_filter_xla_spew(proc.stderr.decode(errors="replace")))
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{platform} bench child: rc={proc.returncode}, no JSON line",
          file=sys.stderr)
    return None


def _run_failover_child(platform: str, timeout_s: int):
    """Run the failover-benchmark child; parsed JSON dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--child-failover", platform],
            env=_child_env(platform),
            cwd=_REPO_ROOT,
            timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print(f"{platform} failover child: timeout ({timeout_s}s)",
              file=sys.stderr)
        return None
    sys.stderr.write(_filter_xla_spew(proc.stderr.decode(errors="replace")))
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{platform} failover child: rc={proc.returncode}, no JSON "
          "line", file=sys.stderr)
    return None


def _run_serve_child(platform: str, timeout_s: int):
    """Run the serving-benchmark child; parsed JSON dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--child-serve", platform],
            env=_child_env(platform),
            cwd=_REPO_ROOT,
            timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print(f"{platform} serve child: timeout ({timeout_s}s)",
              file=sys.stderr)
        return None
    sys.stderr.write(_filter_xla_spew(proc.stderr.decode(errors="replace")))
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{platform} serve child: rc={proc.returncode}, no JSON line",
          file=sys.stderr)
    return None


def topo_main() -> int:
    """``bench.py --topology``: the ISSUE-11 interconnect study.

    Reports the invalidation-storm cost (TOPO_r11.json) of every
    non-ideal topology under the unicast / multicast / combining
    delivery variants: run cycles, slowdown over ideal, the topo
    counters, and the per-link stats.  The numbers are *model* output
    — deterministic cycle counts from the spec engine, a pure function
    of config + trace (no wall clock anywhere) — and every topology is
    cross-checked against the XLA engine (dumps + cycles + counters
    must agree exactly) before it is reported.  Spec-engine timing on
    CPU measures nothing representative, so CPU runs are tagged
    ``indicative: false``.
    """
    import dataclasses

    from hpa2_tpu.analysis.topology import (
        VARIANTS, storm_run, storm_traces)
    from hpa2_tpu.config import InterconnectConfig, SystemConfig

    def _int(name, default):
        try:
            return int(os.environ.get(name, str(default)))
        except ValueError:
            return default

    nodes = _int("HPA2_TOPO_NODES", 8)
    rounds = _int("HPA2_TOPO_ROUNDS", 6)
    bandwidth = _int("HPA2_TOPO_BANDWIDTH", 1)
    base_cfg = SystemConfig(num_procs=nodes, max_instr_num=0)
    traces = storm_traces(base_cfg, rounds)
    ideal_cycles, _, _ = storm_run(base_cfg, traces)

    try:
        import jax

        on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    except Exception:
        on_tpu = False

    def _cross_check(cfg) -> bool:
        """XLA engine agrees with the spec engine byte-for-byte."""
        from hpa2_tpu.ops.engine import JaxEngine

        sp = __import__(
            "hpa2_tpu.models.spec_engine", fromlist=["SpecEngine"]
        ).SpecEngine(cfg, [list(t) for t in traces])
        sp.run()
        jx = JaxEngine(cfg, [list(t) for t in traces]).run()
        return (
            [dataclasses.asdict(d) for d in sp.final_dumps()]
            == [dataclasses.asdict(d) for d in jx.final_dumps()]
            and sp.cycle == jx.cycle
            and sp.link_stats()["traversals"]
            == jx.link_stats()["traversals"]
        )

    topos = {}
    agree = True
    for topo in ("mesh2d", "torus2d", "hierarchical"):
        rows = {}
        for vname, kw in VARIANTS:
            cfg = dataclasses.replace(
                base_cfg,
                interconnect=InterconnectConfig(
                    topology=topo, link_bandwidth=bandwidth, **kw
                ),
            )
            cycles, stats, link = storm_run(cfg, traces)
            rows[vname] = {
                "cycles": cycles,
                "slowdown_over_ideal": round(cycles / ideal_cycles, 3),
                "topo_delay_cycles": stats.get("topo_delay_cycles", 0),
                "topo_multicast_saved": stats.get(
                    "topo_multicast_saved", 0
                ),
                "topo_combined": stats.get("topo_combined", 0),
                "links": link,
            }
        try:
            ok = _cross_check(dataclasses.replace(
                base_cfg,
                interconnect=InterconnectConfig(
                    topology=topo, link_bandwidth=bandwidth
                ),
            ))
        except Exception as e:  # cross-check must never hide the data
            ok = False
            rows["cross_check_error"] = str(e)
        agree = agree and ok
        rows["spec_jax_agree"] = ok
        topos[topo] = rows

    mc = topos["mesh2d"]
    result = {
        "metric": "invalidation_storm_slowdown_mesh2d_unicast",
        "value": mc["unicast"]["slowdown_over_ideal"],
        "unit": "x ideal cycles",
        "platform": "tpu" if on_tpu else "cpu",
        "indicative": on_tpu,
        "nodes": nodes,
        "storm_rounds": rounds,
        "link_bandwidth": bandwidth,
        "ideal_cycles": ideal_cycles,
        "spec_jax_agree_all": agree,
        "topologies": topos,
    }
    print(json.dumps(result))
    return 0


def protocol_main() -> int:
    """``bench.py --protocol``: the ISSUE-13 protocol/directory study
    (PROTO_r13.json).

    A/B of the compiled protocol variants on one sharing-heavy
    workload: per-protocol run cycles and coherence-event counters
    (invalidations, MESIF forwards, MOESI ownership transfers), plus
    the directory-format rows on a wide geometry where limited-pointer
    overflow and coarse-vector rounding actually fire.  Like the
    topology study, the numbers are *model* output — deterministic
    cycle/counter values from the spec engine, a pure function of
    config + trace — and every row is cross-checked against the XLA
    engine (dumps + counters must agree exactly) before it is
    reported.  CPU runs are tagged ``indicative: false`` (nothing here
    is wall-clock anyway).
    """
    import dataclasses

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.models.protocol import Instr
    from hpa2_tpu.models.spec_engine import SpecEngine
    from hpa2_tpu.ops.engine import JaxEngine
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    def _int(name, default):
        try:
            return int(os.environ.get(name, str(default)))
        except ValueError:
            return default

    nodes = _int("HPA2_PROTO_NODES", 8)
    instrs = _int("HPA2_PROTO_INSTRS", 48)
    batch = _int("HPA2_PROTO_BATCH", 4)
    wide_nodes = _int("HPA2_PROTO_WIDE_NODES", 18)

    try:
        import jax

        on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    except Exception:
        on_tpu = False

    def _traces_for(cfg, seed):
        """Sharing-heavy deterministic workload: uniform random folded
        onto the first few homes so lines are contended (the regime
        where the protocols actually differ)."""
        op, addr, val, _ = gen_uniform_random_arrays(
            cfg, batch, instrs, seed=seed
        )
        addr = addr % (3 * cfg.mem_size)
        return [
            [
                [
                    Instr("W", int(a), int(v)) if o == 1
                    else Instr("R", int(a))
                    for o, a, v in zip(op[b, m], addr[b, m], val[b, m])
                ]
                for m in range(cfg.num_procs)
            ]
            for b in range(batch)
        ]

    _KEYS = ("msgs_total", "invalidations", "forwards",
             "owner_transfers", "dir_overflows", "evictions")

    def _ab_row(cfg, seed):
        """Summed spec counters over the batch + XLA agreement."""
        totals = {"cycles": 0}
        agree = True
        for traces in _traces_for(cfg, seed):
            sp = SpecEngine(cfg, traces)
            sp.run(max_cycles=200_000)
            st = sp.stats()
            totals["cycles"] += sp.cycle
            for k in _KEYS:
                totals[k] = totals.get(k, 0) + st.get(k, 0)
            jx = JaxEngine(cfg, traces, max_cycles=200_000).run()
            agree = agree and (
                [dataclasses.asdict(d) for d in sp.final_dumps()]
                == [dataclasses.asdict(d) for d in jx.final_dumps()]
                and sp.cycle == jx.cycle
            )
        totals["spec_jax_agree"] = agree
        return totals

    sem = Semantics().robust()
    protocols = {}
    for protocol in ("mesi", "moesi", "mesif"):
        cfg = SystemConfig(num_procs=nodes, semantics=sem,
                           protocol=protocol)
        protocols[protocol] = _ab_row(cfg, seed=13)

    formats = {}
    for fmt in ("full", "limited:2", "coarse:4"):
        cfg = SystemConfig(num_procs=wide_nodes, cache_size=2,
                           mem_size=8, msg_buffer_size=256,
                           semantics=sem, directory_format=fmt)
        formats[fmt] = _ab_row(cfg, seed=9)

    agree = all(r["spec_jax_agree"]
                for r in list(protocols.values()) + list(formats.values()))
    mesi_msgs = max(protocols["mesi"]["msgs_total"], 1)
    result = {
        "metric": "protocol_traffic_ratio_moesi_over_mesi",
        "value": round(
            protocols["moesi"]["msgs_total"] / mesi_msgs, 4
        ),
        "unit": "x MESI msgs on the shared-hot workload",
        "platform": "tpu" if on_tpu else "cpu",
        "indicative": on_tpu,
        "nodes": nodes,
        "wide_nodes": wide_nodes,
        "instrs_per_core": instrs,
        "batch": batch,
        "spec_jax_agree_all": agree,
        "protocols": protocols,
        "directory_formats": formats,
    }
    print(json.dumps(result))
    return 0


def serve_main() -> int:
    """``bench.py --serve``: the always-on serving benchmark, same
    probe-in-subprocess discipline as the headline bench; always one
    JSON line."""
    tpu_ok = _probe_tpu()
    result = None
    if tpu_ok:
        result = _run_serve_child("tpu", _TPU_CHILD_TIMEOUT_S)
    if result is None:
        result = _run_serve_child("cpu", _CPU_CHILD_TIMEOUT_S)
        if result is not None and tpu_ok:
            result["note"] = "tpu serve child failed; cpu smoke result"
    if result is None:
        result = {
            "metric": "serving_sustained_ops_per_sec",
            "value": 0.0,
            "unit": "RD/WR ops/sec",
            "platform": None,
            "indicative": False,
            "note": "all serve bench paths failed (tpu probe "
                    f"{'ok' if tpu_ok else 'failed'}; see stderr)",
        }
    print(json.dumps(result))
    return 0


def failover_main() -> int:
    """``bench.py --failover``: the fault-tolerance benchmark, same
    probe-in-subprocess discipline as the headline bench; always one
    JSON line."""
    tpu_ok = _probe_tpu()
    result = None
    if tpu_ok:
        result = _run_failover_child("tpu", _TPU_CHILD_TIMEOUT_S)
    if result is None:
        result = _run_failover_child("cpu", _CPU_CHILD_TIMEOUT_S)
        if result is not None and tpu_ok:
            result["note"] = "tpu failover child failed; cpu smoke result"
    if result is None:
        result = {
            "metric": "failover_recovery_overhead_s",
            "value": None,
            "unit": "seconds",
            "platform": None,
            "indicative": False,
            "note": "all failover bench paths failed (tpu probe "
                    f"{'ok' if tpu_ok else 'failed'}; see stderr)",
        }
    print(json.dumps(result))
    return 0


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--compile-gate":
        return compile_gate_main()
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        return child_main(
            sys.argv[2],
            len(sys.argv) < 4 or sys.argv[3] == "1",
            sys.argv[4] if len(sys.argv) > 4 else "",
        )
    if len(sys.argv) >= 3 and sys.argv[1] == "--child-serve":
        return serve_child_main(sys.argv[2])
    if len(sys.argv) >= 3 and sys.argv[1] == "--child-failover":
        return failover_child_main(sys.argv[2])
    if "--data-shards" in sys.argv:
        # split the ensemble over N local devices (DataShardedPallasEngine);
        # carried to the children via the environment
        i = sys.argv.index("--data-shards")
        try:
            os.environ["HPA2_BENCH_DATA_SHARDS"] = str(
                int(sys.argv[i + 1])
            )
        except (IndexError, ValueError):
            print("usage: bench.py [--data-shards N]", file=sys.stderr)
            return 2
    if "--node-shards" in sys.argv:
        # split each system's node planes over N devices
        # (NodeShardedPallasEngine, targeted cross-shard exchange);
        # composes with --data-shards into a 2-D data x node mesh
        i = sys.argv.index("--node-shards")
        try:
            os.environ["HPA2_BENCH_NODE_SHARDS"] = str(
                int(sys.argv[i + 1])
            )
        except (IndexError, ValueError):
            print("usage: bench.py [--node-shards N]", file=sys.stderr)
            return 2
    if "--exchange-mode" in sys.argv:
        # cross-shard transport schedule for --node-shards runs (a2a
        # default; pairwise is the pre-batched serial-round baseline)
        i = sys.argv.index("--exchange-mode")
        try:
            mode = sys.argv[i + 1]
            if mode not in ("pairwise", "a2a", "butterfly", "hier"):
                raise ValueError(mode)
            os.environ["HPA2_BENCH_EXCHANGE_MODE"] = mode
        except (IndexError, ValueError):
            print(
                "usage: bench.py [--exchange-mode "
                "pairwise|a2a|butterfly|hier]",
                file=sys.stderr,
            )
            return 2
    if "--trace-len-dist" in sys.argv:
        # heterogeneous per-system trace lengths (uniform|zipf over
        # [max/spread, max]); the artifact then also carries the static
        # occupancy model's stats for the generated length distribution
        i = sys.argv.index("--trace-len-dist")
        try:
            dist = sys.argv[i + 1]
            if dist not in ("uniform", "zipf"):
                raise ValueError(dist)
            os.environ["HPA2_BENCH_TRACE_DIST"] = dist
        except (IndexError, ValueError):
            print("usage: bench.py [--trace-len-dist uniform|zipf]",
                  file=sys.stderr)
            return 2
    if "--trace-len-spread" in sys.argv:
        i = sys.argv.index("--trace-len-spread")
        try:
            os.environ["HPA2_BENCH_TRACE_SPREAD"] = str(
                float(sys.argv[i + 1])
            )
        except (IndexError, ValueError):
            print("usage: bench.py [--trace-len-spread RATIO]",
                  file=sys.stderr)
            return 2
    if "--packed" in sys.argv:
        # uint8/uint16 packed state planes (ISSUE 6): ~2x the lanes
        # per VMEM budget; bit-exact vs the int32 layout
        os.environ["HPA2_BENCH_PACKED"] = "1"
    if "--no-elide" in sys.argv:
        # lockstep A/B baseline for the event-driven cycle elision
        # (ISSUE 12): bit-identical results, one device step per
        # simulated cycle
        os.environ["HPA2_BENCH_NO_ELIDE"] = "1"
    if "--schedule-resident" in sys.argv:
        # occupancy scheduler with this many device-resident lanes;
        # fused single-program by default, --host-barriers for the
        # PR-5 one-launch-per-interval loop
        i = sys.argv.index("--schedule-resident")
        try:
            os.environ["HPA2_BENCH_SCHEDULE_RESIDENT"] = str(
                int(sys.argv[i + 1])
            )
        except (IndexError, ValueError):
            print("usage: bench.py [--schedule-resident N]",
                  file=sys.stderr)
            return 2
    if "--host-barriers" in sys.argv:
        os.environ["HPA2_BENCH_HOST_BARRIERS"] = "1"
    if "--serve" in sys.argv:
        # always-on serving benchmark (ISSUE 10): sized via the
        # HPA2_SERVE_* env knobs; --data-shards composes (dispatched
        # after the argv->env parsing above so it takes effect)
        return serve_main()
    if "--failover" in sys.argv:
        # fault-tolerance benchmark (ISSUE 16): recovery latency per
        # failure kind + wire-sever blackout; sized via HPA2_SERVE_* /
        # HPA2_FAILOVER_AT
        return failover_main()
    if "--topology" in sys.argv:
        # interconnect sensitivity study (ISSUE 11): sized via the
        # HPA2_TOPO_* env knobs; model output, spec/XLA cross-checked
        return topo_main()
    if "--protocol" in sys.argv:
        # protocol/directory-format A/B study (ISSUE 13): sized via
        # the HPA2_PROTO_* env knobs; model output, spec/XLA
        # cross-checked
        return protocol_main()

    tpu_ok = _probe_tpu()
    result = None
    if tpu_ok:
        pallas_ok, pallas_err = _compile_gate()
        if not pallas_ok:
            print(f"pallas compile gate FAILED: {pallas_err}",
                  file=sys.stderr)
        result = _run_child("tpu", _TPU_CHILD_TIMEOUT_S, pallas_ok,
                            pallas_err)
        if result is not None and not pallas_ok:
            result["pallas_error"] = pallas_err
        if result is not None and result.get("platform") == "tpu":
            _record_last_tpu(result)
    if result is None:
        result = _run_child("cpu", _CPU_CHILD_TIMEOUT_S, True, "")
        if result is not None:
            why = (
                "tpu measurement child failed"
                if tpu_ok
                else "tpu unavailable"
            )
            result["note"] = (
                result.get("note", "") + f" {why}; cpu smoke result"
            ).strip()
            last = _load_last_tpu()
            if last is not None:
                # carry the last real TPU measurement, clearly dated
                # and marked stale, so a tunnel-down round is not
                # mistaken for a perf regression
                result["last_good_tpu"] = {
                    "stale": True, **{
                        k: last[k]
                        for k in ("value", "vs_baseline", "engine",
                                  "batch", "jax_instrs", "jax_seconds",
                                  "recorded_at")
                        if k in last
                    },
                }
    if result is None:  # every path failed: still emit the JSON line
        result = {
            "metric": "sim_ops_per_sec_jax",
            "value": 0.0,
            "unit": "RD/WR ops/sec",
            "vs_baseline": None,
            "engine": None,
            "platform": None,
            "note": "all bench paths failed (tpu probe "
                    f"{'ok' if tpu_ok else 'failed'}; see stderr)",
        }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
