#!/usr/bin/env bash
# Tier-1 verification — the exact command from ROADMAP.md ("Tier-1
# verify"), kept here so every session runs the same gate instead of
# retyping (and subtly varying) it.  Exit code is pytest's; the
# DOTS_PASSED line gives a collection-error-proof pass count.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
# Virtual 8-device CPU mesh so the multi-chip tests (virtual_mesh
# marker) run in tier-1 on any host.  Conftest's re-exec honors an
# existing device-count flag, so exporting here makes the mesh
# explicit rather than relying on the re-exec default; a pre-set
# count is respected (the marked tests skip cleanly if it is < 8).
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="--xla_force_host_platform_device_count=8${XLA_FLAGS:+ $XLA_FLAGS}" ;;
esac
# Occupancy-scheduler model smoke (pure numpy, ~1s): nonzero rc means
# the barrier policy predicts doing MORE work than the lockstep bound
# (a policy bug) — fail fast, before spending the pytest budget.
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m hpa2_tpu.analysis occupancy > /dev/null; then
  echo "TIER1: analysis occupancy smoke failed" >&2
  exit 1
fi
# Packed+fused smoke (~30s, CPU interpret): the ISSUE-6 fast path —
# uint8/uint16 packed planes under the fused single-program scheduler
# — must stay bit-exact against the unscheduled int32 reference, and
# report exactly one device program.  Catches packed/fused wiring
# breaks before the pytest budget is spent.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import numpy as np
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.utils.trace import gen_heterogeneous_random_arrays

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
kw = dict(block=4, cycles_per_call=32, snapshots=False, trace_window=8,
          gate=True)
arrays = gen_heterogeneous_random_arrays(cfg, 16, 24, dist="zipf",
                                         spread=4.0, seed=1)
ref = PallasEngine(cfg, *arrays, **kw).run()
eng = PallasEngine(cfg, *arrays, packed=True,
                   schedule=Schedule(resident=8), **kw).run()
assert eng.occupancy.device_programs == 1
assert eng.occupancy.host_barriers == 0
assert all(eng.system_final_dumps(s) == ref.system_final_dumps(s)
           for s in range(16))
EOF
then
  echo "TIER1: packed+fused smoke failed" >&2
  exit 1
fi
# Node-shard smoke (~45s, virtual mesh): the ISSUE-7/ISSUE-15 fast
# path — one system's node planes split over the mesh's node axis with
# the targeted batched exchange, composed with data sharding on the
# 2x2 mesh AND at node_shards=4 under a non-default collective
# schedule — must stay bit-exact against the single-chip jax engine's
# dumps, ship cross-shard traffic, and report the exchange telemetry.
# Catches exchange/transport wiring breaks cheaply.
if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import dataclasses
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine
from hpa2_tpu.utils.trace import gen_uniform_random, traces_to_arrays

cfg = SystemConfig(num_procs=8, semantics=Semantics().robust())
batch = [gen_uniform_random(cfg, 10, seed=60 + s) for s in range(2)]
eng = NodeShardedPallasEngine(
    cfg, *traces_to_arrays(cfg, batch), node_shards=2, data_shards=2,
    snapshots=False, cycles_per_call=16).run()
assert eng.cross_shard_msgs > 0
refs = [JaxEngine(cfg, traces).run() for traces in batch]
for s, ref in enumerate(refs):
    assert [d.__dict__ for d in eng.system_final_dumps(s)] == [
        d.__dict__ for d in ref.final_dumps()], f"system {s} diverged"
# 4-device rung on the round-15 transport: butterfly schedule,
# telemetry keys live
eng4 = NodeShardedPallasEngine(
    dataclasses.replace(cfg, exchange_mode="butterfly"),
    *traces_to_arrays(cfg, batch), node_shards=4,
    snapshots=False, cycles_per_call=16).run()
for s, ref in enumerate(refs):
    assert [d.__dict__ for d in eng4.system_final_dumps(s)] == [
        d.__dict__ for d in ref.final_dumps()], f"x4 system {s} diverged"
stats = eng4.stats()
assert stats["exchange_sent"] > 0, stats
assert stats["exchange_slot_hwm"] >= 1, stats
EOF
then
  echo "TIER1: node-shard smoke failed" >&2
  exit 1
fi
# Interconnect smoke (~20s, CPU): the ISSUE-11 topology model — the
# `analysis topology` sensitivity table must render, and an explicit
# topology="ideal" config (with inert non-default knobs) must stay
# bit-exact against the default pre-topology config on both the spec
# and jax engines.  Catches delivery-gate wiring breaks cheaply.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import dataclasses
from hpa2_tpu.analysis.topology import topology_table
from hpa2_tpu.config import InterconnectConfig, Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.utils.trace import gen_uniform_random

out = topology_table(nodes=4, rounds=2, topologies=["mesh2d"])
assert "unicast" in out and "mcast+comb" in out

cfg = SystemConfig(num_procs=4, max_instr_num=0,
                   semantics=Semantics().robust())
alt = dataclasses.replace(cfg, interconnect=InterconnectConfig(
    topology="ideal", hop_latency=5, link_bandwidth=2))
traces = gen_uniform_random(cfg, 20, seed=3)
ref = JaxEngine(cfg, traces).run()
got = JaxEngine(alt, traces).run()
spec = SpecEngine(alt, [list(t) for t in traces])
spec.run()
as_dicts = lambda dumps: [d.__dict__ for d in dumps]
assert as_dicts(got.final_dumps()) == as_dicts(ref.final_dumps())
assert as_dicts(spec.final_dumps()) == as_dicts(ref.final_dumps())
assert got.cycle == ref.cycle == spec.cycle
assert got.stats() == ref.stats()
EOF
then
  echo "TIER1: interconnect smoke failed" >&2
  exit 1
fi
# Serving smoke (~30s, CPU interpret): the ISSUE-10 always-on loop —
# a short Poisson feed admitted into resident lanes must produce
# byte-identical dumps to the one-shot scheduled batch run, with
# every session program's jit cache at exactly one entry (the
# zero-recompile pin).  Catches admission/barrier wiring breaks.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import numpy as np
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.serving import (
    ListJobSource, poisson_arrivals, serve, synthetic_jobs)

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
jobs = synthetic_jobs(cfg, 8, 24, seed=7, spread=3.0,
                      arrivals=poisson_arrivals(8, 200.0, seed=1))
ref = PallasEngine(
    cfg,
    np.stack([j.tr_op for j in jobs]),
    np.stack([j.tr_addr for j in jobs]),
    np.stack([j.tr_val for j in jobs]),
    np.stack([j.tr_len for j in jobs]),
    block=4, trace_window=8, snapshots=False,
    schedule=Schedule(resident=4, fused=False),
).run()
results, stats = serve(cfg, ListJobSource(jobs, timed=True),
                       backend="pallas", resident=4, window=8, block=4)
assert len(results) == 8
for s, j in enumerate(jobs):
    r = next(r for r in results if r.job_id == j.job_id)
    assert r.dumps == ref.system_final_dumps(s), j.job_id
assert all(c == 1 for c in stats.compile_counts.values()), \
    stats.compile_counts
EOF
then
  echo "TIER1: serving smoke failed" >&2
  exit 1
fi
# Service smoke (~30s, CPU interpret): the ISSUE-14 service plane — a
# loopback framed-wire client submits a 2-tenant feed, every SUBMIT
# must draw an ACK whose seq fixes the admission order, results must
# stream back over the connection, and the served dumps must stay
# byte-identical to the one-shot scheduled run under fair-drr.
# Catches wire/ledger/scheduler-policy wiring breaks cheaply.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import threading
import numpy as np
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.pallas_engine import PallasEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.serving import synthetic_jobs, job_to_record, serve
from hpa2_tpu.service import TenantTable, WireClient, WireJobSource

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
jobs = synthetic_jobs(cfg, 8, 24, seed=7, spread=3.0)
recs = [job_to_record(j) for j in jobs]
for i, r in enumerate(recs):
    r["tenant"] = ("a", "b")[i % 2]
ref = PallasEngine(
    cfg,
    np.stack([j.tr_op for j in jobs]),
    np.stack([j.tr_addr for j in jobs]),
    np.stack([j.tr_val for j in jobs]),
    np.stack([j.tr_len for j in jobs]),
    block=4, trace_window=8, snapshots=False,
    schedule=Schedule(resident=4, fused=False),
).run()
src = WireJobSource(cfg, tenants=TenantTable.parse("a:2,b:1"),
                    credits=16)
acks, streamed = [], []
def client():
    with WireClient(*src.address) as cli:
        for r in recs:
            acks.append(cli.submit(r))
        streamed.extend(cli.finish())
t = threading.Thread(target=client)
t.start()
results, stats = serve(cfg, src, backend="pallas", resident=4,
                       window=8, block=4, policy="fair-drr",
                       emit=src.deliver,
                       tenant_weights=src.tenant_weights)
t.join(timeout=30)
assert [a["seq"] for a in acks] == list(range(8)), acks
assert sorted(r["id"] for r in streamed) == sorted(
    j.job_id for j in jobs)
for s, j in enumerate(jobs):
    r = next(r for r in results if r.job_id == j.job_id)
    assert r.dumps == ref.system_final_dumps(s), j.job_id
assert "tenant_share" in stats.occupancy, stats.occupancy
assert all(c == 1 for c in stats.compile_counts.values()), \
    stats.compile_counts
EOF
then
  echo "TIER1: service smoke failed" >&2
  exit 1
fi
# Elision smoke (~30s, CPU): the ISSUE-12 event-driven loop — a
# scheduled zipf hot-set run must actually elide cycles, stay
# byte-identical to the elide=False lockstep run, and the exact-replay
# model (`analysis elision`) must reproduce the device counters
# bit-for-bit.  Catches propose/fast-forward wiring breaks cheaply.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import dataclasses
from hpa2_tpu.analysis.elision import elision_table
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.ops.engine import BatchJaxEngine
from hpa2_tpu.ops.schedule import Schedule
from hpa2_tpu.utils.trace import gen_hot_hit_zipf

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
batch = [gen_hot_hit_zipf(cfg, 48, seed=20 + s) for s in range(4)]
kw = dict(schedule=Schedule(interval=16, fused=False))
eng = BatchJaxEngine(cfg, batch, **kw).run()
ref = BatchJaxEngine(dataclasses.replace(cfg, elide=False), batch,
                     **kw).run()
occ = eng.occupancy.as_dict()
assert occ["elided_cycles"] > 0, occ
assert "elided_cycles" not in ref.occupancy.as_dict()
for s in range(4):
    assert eng.system_final_dumps(s) == ref.system_final_dumps(s), s
    assert eng.system_snapshots(s) == ref.system_snapshots(s), s

# model == device, asserted inside the table builder (rc != 0 on any
# mismatch)
table, rc = elision_table(procs=4, instrs=64, spreads=(8.0,))
assert rc == 0, table
EOF
then
  echo "TIER1: elision smoke failed" >&2
  exit 1
fi
# Protocol smoke (~20s, CPU): the ISSUE-13 compiled-table layer — the
# lowered MESI planes must match their pinned digest byte-for-byte
# (the reference protocol is frozen; tests/test_protocols.py carries
# the same pin), and a tiny MOESI run must agree spec<->jax while
# actually transferring ownership.  Catches lowering/wiring breaks
# before the pytest budget is spent.
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
from hpa2_tpu.config import Semantics, SystemConfig
from hpa2_tpu.models.spec_engine import SpecEngine
from hpa2_tpu.ops.engine import JaxEngine
from hpa2_tpu.protocols.compiler import planes_for
from hpa2_tpu.utils.trace import gen_uniform_random

assert planes_for("mesi", Semantics()).digest() == (
    "10158e4dc973a48cec932b2cadc9c665"
    "18770217695955ea8f099662396f27c0"
), "compiled MESI planes drifted from the pinned digest"

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust(),
                   protocol="moesi")
traces = gen_uniform_random(cfg, 24, seed=13)
jx = JaxEngine(cfg, traces).run()
spec = SpecEngine(cfg, [list(t) for t in traces])
spec.run()
as_dicts = lambda dumps: [d.__dict__ for d in dumps]
assert as_dicts(spec.final_dumps()) == as_dicts(jx.final_dumps())
assert spec.cycle == jx.cycle
assert spec.stats().get("owner_transfers", 0) > 0
assert jx.stats()["owner_transfers"] == spec.stats()["owner_transfers"]
EOF
then
  echo "TIER1: protocol smoke failed" >&2
  exit 1
fi
# Chaos smoke (~60s, CPU): the ISSUE-16 fault-tolerance supervisor —
# a seeded kill on the served pallas path must recover by checkpointed
# migration onto the jax backend with dumps byte-identical to an
# unfailed run (migrations >= 1), and a shed-threshold wire server
# must NACK batch-class overload with the shed accounted in the stats.
# Catches injector/recovery/schedule-preservation wiring breaks.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu python - > /dev/null <<'EOF'
import tempfile
import threading

from hpa2_tpu.config import FailurePlan, Semantics, SystemConfig
from hpa2_tpu.service import WireClient, WireJobSource, WireNack
from hpa2_tpu.serving import (
    ListJobSource, job_to_record, serve, supervised_serve,
    synthetic_jobs)

cfg = SystemConfig(num_procs=4, semantics=Semantics().robust())
jobs = synthetic_jobs(cfg, 8, 24, seed=7, spread=3.0)
dump_map = lambda rs: {r.job_id: [repr(d) for d in r.dumps] for r in rs}

base, _ = serve(cfg, ListJobSource(jobs), backend="pallas",
                resident=4, window=16)
want = dump_map(base)
with tempfile.TemporaryDirectory() as td:
    res, st = supervised_serve(
        cfg, ListJobSource(jobs), plan=FailurePlan.parse("kill@3", seed=1),
        checkpoint_dir=td, backend="pallas", resident=4, window=16)
rec = st.occupancy["recovery"]
assert dump_map(res) == want, "post-recovery dumps differ from unfailed run"
assert rec["migrations"] >= 1, rec
assert rec["failures_detected"] == 1, rec

# graceful degradation: 1-slot queue, batch-class jobs shed loudly
recs = [job_to_record(j) for j in jobs]
for i, r in enumerate(recs):
    if i % 2:
        r["class"] = "batch"
    else:
        r["deadline"] = 8
src = WireJobSource(cfg, shed_threshold=1)
shed = []
def client():
    with WireClient(*src.address) as cli:
        for r in recs:
            try:
                cli.submit(r)
            except WireNack as e:
                assert e.shed, e
                shed.append(r["id"])
        cli.finish()
t = threading.Thread(target=client, daemon=True)
t.start()
_, st2 = serve(cfg, src, backend="pallas", resident=4, window=16,
               emit=src.deliver)
t.join(timeout=60)
assert shed, "shed_threshold=1 never shed a batch-class job"
assert st2.occupancy.get("shed_jobs") == len(shed), (
    st2.occupancy.get("shed_jobs"), len(shed))
EOF
then
  echo "TIER1: chaos smoke failed" >&2
  exit 1
fi
# Contracts smoke (~3min, virtual mesh): the ISSUE-17 compiled-program
# contract engine — every registered jaxpr/HLO contract point (XLA run
# loop, Pallas cycle body, serving sessions, recovery-resume, node-
# and data-sharded programs) must match its checked-in pins.  A drift
# here means a structural change to a traced program that no
# behavioral test may notice (an extra collective, a grown hot loop, a
# lost donation) — fail with the drift diff before the pytest budget.
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m hpa2_tpu.analysis contracts --check; then
  echo "TIER1: compiled-program contracts drifted" >&2
  exit 1
fi
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# A file that fails to *collect* silently shrinks the pass count — the
# run must fail even if every collected test passed and the pytest exit
# code got rewritten somewhere (plugin, timeout, shell edge).  The
# summary line reports "N error(s)" exactly when collection errored.
if grep -aqE '(^|, )[0-9]+ errors? in [0-9]' /tmp/_t1.log; then
    echo "TIER1: pytest reported collection errors; failing" >&2
    [ "$rc" -eq 0 ] && rc=1
fi
exit $rc
