"""Compile-only probe of the Pallas kernel on the TPU (no execution of
the full bench).  Exit 0 + one JSON line on success; nonzero + the
Mosaic error tail on failure.  Run under the TPU env."""

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def main() -> int:
    import jax
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops import pallas_engine as pe

    config = SystemConfig(
        num_procs=8, msg_buffer_size=32, semantics=Semantics().robust()
    )
    b, bb, k = 128, 128, 8
    tr_op = np.zeros((b, 8, 16), np.int32)
    tr_addr = np.zeros((b, 8, 16), np.int32)
    tr_val = np.zeros((b, 8, 16), np.int32)
    tr_len = np.full((b, 8), 16, np.int32)
    state, traces = pe._init_transposed(config, tr_op, tr_addr, tr_val, tr_len)
    state = {f: jax.numpy.asarray(v) for f, v in state.items()}
    traces = {f: jax.numpy.asarray(v) for f, v in traces.items()}
    call = pe._build_call(config, b, bb, k, False)
    t0 = time.time()
    lowered = call.lower(state, traces)
    compiled = lowered.compile()
    dt = time.time() - t0
    print(json.dumps({"ok": True, "compile_s": round(dt, 1),
                      "platform": jax.devices()[0].platform}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
