"""Compile-only probe of the Pallas kernel on the TPU (no execution of
the full bench).  Exit 0 + one JSON line on success; nonzero + the
Mosaic error tail on failure.  Run under the TPU env.

By default this lowers the HBM-STREAMING whole-run program (the thing
the bench actually executes) and reports the compiler-measured VMEM
figure next to the static budget model's prediction
(hpa2_tpu/analysis/vmem.py), so one live tunnel session settles the
model-vs-compiler agreement check.  ``--legacy`` probes the old
per-call VMEM-resident kernel instead; ``--block/--window/--gate``
sweep the shape (block 1024/2048 are the levers the model predicts
now fit under the 16 MiB cap).
"""

import argparse
import json
import re
import sys
import time

sys.path.insert(0, "/root/repo")


def _measured_vmem_from_error(msg: str):
    """Mosaic over-budget errors name the request in bytes; scrape it
    so a failed compile still yields a measured figure."""
    m = re.search(r"(\d+)\s*bytes.{0,80}(vmem|VMEM)", msg) or re.search(
        r"(vmem|VMEM).{0,120}?(\d{6,})", msg)
    if not m:
        return None
    digits = [g for g in m.groups() if g and g.isdigit()]
    return int(digits[0]) if digits else None


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--block", type=int, default=1024)
    p.add_argument("--window", type=int, default=16)
    p.add_argument("--gate", action="store_true")
    p.add_argument("--legacy", action="store_true",
                   help="probe the non-streaming per-call kernel")
    args = p.parse_args()

    import jax
    import numpy as np

    from hpa2_tpu.analysis.vmem import measured_vmem_bytes, vmem_budget
    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    config = SystemConfig(
        num_procs=8, msg_buffer_size=16, semantics=Semantics().robust()
    )
    b, t = args.block, 2 * args.window
    tr_op = np.zeros((b, 8, t), np.int32)
    tr_addr = np.zeros((b, 8, t), np.int32)
    tr_val = np.zeros((b, 8, t), np.int32)
    tr_len = np.full((b, 8), t, np.int32)
    eng = PallasEngine(config, tr_op, tr_addr, tr_val, tr_len,
                       block=args.block, cycles_per_call=8,
                       interpret=False, snapshots=False,
                       trace_window=args.window, gate=args.gate,
                       stream=not args.legacy)
    bud = vmem_budget(config, args.block, args.window,
                      snapshots=False, gate=args.gate,
                      stream=not args.legacy)
    out = {
        "block": args.block, "window": args.window,
        "gate": args.gate, "stream": not args.legacy,
        "model_vmem_bytes": bud.total_bytes,
        "model_fits": bud.fits,
    }
    t0 = time.time()
    try:
        compiled = eng.lower_run(max_cycles=10_000).compile()
    except Exception as e:  # noqa: BLE001 - report ANY compile failure
        msg = str(e)
        out.update({
            "ok": False,
            "measured_vmem_bytes": _measured_vmem_from_error(msg),
            "error_tail": msg[-800:],
        })
        print(json.dumps(out))
        return 1
    measured = measured_vmem_bytes(compiled)
    out.update({
        "ok": True,
        "compile_s": round(time.time() - t0, 1),
        "measured_vmem_bytes": measured,
        "platform": jax.devices()[0].platform,
    })
    if measured:
        out["model_vs_measured"] = round(
            bud.total_bytes / measured, 3)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
