"""Compile-only probe of the Pallas kernel on the TPU (no execution of
the full bench).  Exit 0 + one JSON line on success; nonzero + the
Mosaic error tail on failure.  Run under the TPU env."""

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def main() -> int:
    import jax
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    config = SystemConfig(
        num_procs=8, msg_buffer_size=16, semantics=Semantics().robust()
    )
    b, t = 1024, 16
    tr_op = np.zeros((b, 8, t), np.int32)
    tr_addr = np.zeros((b, 8, t), np.int32)
    tr_val = np.zeros((b, 8, t), np.int32)
    tr_len = np.full((b, 8), t, np.int32)
    eng = PallasEngine(config, tr_op, tr_addr, tr_val, tr_len,
                       cycles_per_call=8, interpret=False,
                       snapshots=False)
    t0 = time.time()
    eng._call.lower(eng.state, eng.traces).compile()
    dt = time.time() - t0
    print(json.dumps({"ok": True, "compile_s": round(dt, 1),
                      "platform": jax.devices()[0].platform}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
