#!/usr/bin/env bash
# Static analysis gate: declarative-table checks + spec equivalence,
# the JAX-pitfall/dead-handler lint, the analyzer's mutation self-test,
# and the ASan+UBSan smoke run of the native backend.
#
# The same checks also run inside tier-1 (tests/test_analysis.py,
# tests/test_table_equivalence.py, tests/test_sanitizers.py); this
# script is the fast standalone entry point — no JAX import, a few
# seconds end to end.  Cross-backend equivalence including the JAX and
# native engines: python -m hpa2_tpu.analysis equiv
set -e
cd "$(dirname "$0")/.."

echo "== analysis check (static table checks + spec equivalence) =="
python -m hpa2_tpu.analysis check

echo "== analysis lint (JAX pitfalls, dead handlers) =="
python -m hpa2_tpu.analysis lint

echo "== analyzer mutation self-test =="
python -m hpa2_tpu.analysis mutation-test

echo "== native ASan+UBSan smoke =="
if make -C native asan >/dev/null 2>&1; then
    ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
    UBSAN_OPTIONS=halt_on_error=1 \
        ./native/build/hpa2sim_asan --bench 300 --robust --json
else
    echo "sanitizer toolchain unavailable; skipped"
fi

echo "STATIC_OK"
