#!/usr/bin/env bash
# Static analysis gate: declarative-table checks + spec equivalence,
# the JAX-pitfall/dead-handler lint, the analyzer's mutation self-test,
# the compiled-program contract check (jaxpr/HLO pins over every
# engine path), and the ASan+UBSan smoke run of the native backend.
#
# The same checks also run inside tier-1 (tests/test_analysis.py,
# tests/test_table_equivalence.py, tests/test_sanitizers.py,
# tests/test_contracts.py); this script is the standalone entry point.
# Only the contracts section imports JAX — everything before it is
# AST/table work, a few seconds end to end.  Cross-backend equivalence
# including the JAX and native engines: python -m hpa2_tpu.analysis equiv
set -e
cd "$(dirname "$0")/.."

echo "== analysis check (static table checks + spec equivalence) =="
python -m hpa2_tpu.analysis check

echo "== analysis lint (JAX pitfalls, dead handlers) =="
python -m hpa2_tpu.analysis lint

echo "== analyzer mutation self-test =="
python -m hpa2_tpu.analysis mutation-test

echo "== compiled-program contracts (jaxpr/HLO pins, all engines) =="
# the one section that imports JAX: traces every engine path on the
# virtual 8-device CPU mesh and diffs against the checked-in pins
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m hpa2_tpu.analysis contracts --check

echo "== native ASan+UBSan smoke =="
if make -C native asan >/dev/null 2>&1; then
    ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
    UBSAN_OPTIONS=halt_on_error=1 \
        ./native/build/hpa2sim_asan --bench 300 --robust --json
else
    echo "sanitizer toolchain unavailable; skipped"
fi

echo "STATIC_OK"
