"""One-shot TPU measurement session for round 5.

The axon tunnel has been intermittent (minutes-long windows).  This
orchestrator runs EVERY pending TPU task in one go, each step in its
own timeout-guarded subprocess under the cached-compile env, and
appends one JSON line per step to ``R5_TPU_SESSION.jsonl`` as it
completes — a dropped tunnel mid-session loses only the running step.

Steps, in value order:
  1. probe         — is a TPU visible at all?
  2. vmemprobe     — compile-only streaming-kernel probes at block
                     512/1024/2048 (scripts/probe_compile.py):
                     compiler-measured VMEM vs the static budget
                     model (hpa2_tpu/analysis/vmem.py) — the 10%
                     model-agreement acceptance check
  3. bench         — python bench.py (captures BENCH_LAST_TPU.json)
  4. differential  — scripts/tpu_differential.py (Mosaic-vs-XLA gate)
  5. sweep512      — current bench shape, full-run wall clock
  6. block1024     — PERF.md lever 1: window 8, gate off, block 1024
                     (HBM-streamed kernel; compile fit was the
                     round-4 blocker)
  7. block2048     — the next doubling, streaming kernel, window 8
  8. sweeps        — a few block/window/gate points around the winner
  9. scale4/scale5 — BASELINE.json configs 4-5 (scripts/scale_runs.py)
 10. sweep512_dp   — the shipped bench shape with the ensemble split
                     across every local chip (DataShardedPallasEngine;
                     shards=0 means "all devices")
 11. occupancy512  — occupancy scheduler (schedule=) on the shipped
                     shape over a heterogeneous zipf workload (8x
                     max/median trace-length spread): scheduled vs
                     unscheduled wall-clock + block-segment counters,
                     with a per-system scalars bit-exactness check
 12. fused_occupancy512 — the fused single-program scheduler (packed
                     planes on) vs the PR-5 host-barrier path vs
                     unscheduled on the shipped shape: how many real
                     seconds the removed host barriers buy, with
                     scalars bit-exactness gating both scheduled runs
 13. multichip     — the data_shards scaling ladder + bit-exactness
                     check (scripts/scale_runs.py multichip), which
                     writes MULTICHIP_r06.json with indicative:true
                     pod-slice numbers
 14. nodeshard     — PR-7 node-axis sharding: one system split across
                     4 chips vs the same system on one chip (final
                     dumps bit-exactness gate + measured cross-shard
                     ICI traffic), then the node_shards ladder
                     (scripts/scale_runs.py nodeshard →
                     MULTICHIP_r07.json), the ISSUE-15 old-vs-new
                     exchange A/B ladder (scripts/scale_runs.py
                     nodeshard_ab → MULTICHIP_r08.json) and a
                     sharded-only 4096-node geometry no single chip
                     fits
  nodeshard_x2x4     ISSUE-15 batched-exchange rungs on the reference
                     geometry: 64 nodes at 2 and 4 shards under the
                     a2a schedule plus a butterfly x4 rung, each
                     bit-exactness-gated with the per-cycle collective
                     budget recorded
  elided_nodeshard   ISSUE-15 cycle elision across the shard mesh
                     (NodeShardedEngine, hot-set zipf): elide on/off
                     wall-clock + elided-cycle counters, dumps/cycle
                     bit-identity gate at node_shards=4
 15. serve512      — ISSUE-10 always-on serving at 32768 resident
                     lanes (bench.py --serve with
                     HPA2_SERVE_RESIDENT=32768): sustained ops/sec +
                     p50/p99 job latency under Poisson and heavy-tail
                     arrivals, with the pipelined-vs-serial staging
                     overlap split
  serve_mt512        ISSUE-14 multi-tenant service plane at 32768
                     resident lanes: capacity under fair-drr
                     admission plus the 4-weighted-tenant deadline
                     mix (per-tenant p50/p99 latency, tenant_share,
                     deadline hit rate)
  failover512        ISSUE-16 fault-tolerance supervisor at a served
                     512-resident shape (bench.py --failover):
                     recovery overhead per failure kind (kill/hang/
                     poison), byte-identity vs the unfailed dumps,
                     wire-sever client blackout
  elision512         ISSUE-12 event-driven cycle elision at the
                     shipped batch shape (32768 lanes, zipf 8x
                     private hot sets) on the batched XLA engine:
                     elide on/off wall-clock, elided-cycle /
                     multi-hit counters, full-state bit-identity gate
  topo512            interconnect sensitivity study at a 16-node x
                     24-round invalidation storm (bench.py --topology
                     with HPA2_TOPO_NODES/ROUNDS): rewrites
                     TOPO_r11.json with indicative:true numbers and
                     the spec<->jax agreement verdicts

All measure() steps run the HBM-streaming run program (PallasEngine
default stream=True since the VMEM-wall PR).

Usage: python scripts/r5_tpu_session.py [--skip probe,bench,...]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT_PATH = os.path.join(REPO, "R5_TPU_SESSION.jsonl")


def _env():
    from hpa2_tpu import hostenv

    return hostenv.cache_env(dict(os.environ))


def record(step, payload):
    rec = {"step": step,
           "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    rec.update(payload)
    with open(OUT_PATH, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return rec


def run_py(step, code_or_argv, timeout_s, argv=False):
    cmd = (
        [sys.executable] + code_or_argv
        if argv
        else [sys.executable, "-c", code_or_argv]
    )
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, env=_env(), cwd=REPO, timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired as e:
        # keep whatever the child said before the timeout — the
        # post-mortem needs to distinguish compile-hang from
        # device-wait from mid-run drop
        return record(step, {
            "ok": False,
            "error": f"timeout {timeout_s}s",
            "stdout_tail": (e.stdout or b"").decode(
                errors="replace")[-400:],
            "stderr_tail": (e.stderr or b"").decode(
                errors="replace")[-400:],
        })
    out = proc.stdout.decode(errors="replace")
    last_json = None
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                last_json = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    return record(step, {
        "ok": proc.returncode == 0,
        "rc": proc.returncode,
        "wall_s": round(time.time() - t0, 1),
        "result": last_json,
        "stderr_tail": proc.stderr.decode(errors="replace")[-400:]
        if proc.returncode != 0 else "",
    })


def measure_child(params) -> int:
    """--measure mode: one timed pallas run, one JSON line out.
    Runs in the child interpreter (under the TPU env).  An optional
    8th parameter is the data-shard count (0 = all local devices;
    1 = plain single-device PallasEngine)."""
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine, _SC_CYCLE
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    batch, instrs, block, k, cap, window, gate = params[:7]
    shards = params[7] if len(params) > 7 else 1
    if shards == 0:
        import jax

        shards = len(jax.devices())
    config = SystemConfig(num_procs=8, msg_buffer_size=cap,
                          semantics=Semantics().robust())
    if shards > 1:
        batch = -(-batch // shards) * shards
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=0)

    def build():
        kw = dict(block=block, cycles_per_call=k, snapshots=False,
                  trace_window=window, gate=bool(gate))
        if shards > 1:
            from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

            return DataShardedPallasEngine(
                config, *arrays, data_shards=shards, **kw)
        return PallasEngine(config, *arrays, **kw)

    eng = build()
    t0 = time.perf_counter()
    eng.run(max_cycles=5_000_000)
    warm = time.perf_counter() - t0
    eng2 = build()
    t0 = time.perf_counter()
    eng2.run(max_cycles=5_000_000)
    dt = time.perf_counter() - t0
    cyc = int(np.max(np.asarray(eng2.state["scalars"][_SC_CYCLE])))
    rec = {
        "batch": batch, "instrs": instrs, "block": block, "k": k,
        "cap": cap, "window": window, "gate": gate,
        "instructions": eng2.instructions, "seconds": round(dt, 3),
        "warm_s": round(warm, 1),
        "ops_per_sec": round(eng2.instructions / dt, 1),
        "cycles": cyc,
        "us_per_cycle": round(dt / max(cyc, 1) * 1e6, 2),
    }
    if shards > 1:
        rec["data_shards"] = shards
    print(json.dumps(rec))
    return 0


def measure_occupancy_child(params) -> int:
    """--measure-occupancy mode: heterogeneous (zipf) ensemble, one
    unscheduled and one scheduled run, wall-clock + occupancy
    counters, one JSON line out.  Nonzero exit iff the scheduled
    run's per-system scalars plane (cycle/instr/hit/miss counters,
    schedule-invariant by design) differs from the unscheduled one."""
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.ops.schedule import Schedule
    from hpa2_tpu.utils.trace import gen_heterogeneous_random_arrays

    batch, instrs, block, k, cap, window, gate, spread = params[:8]
    config = SystemConfig(num_procs=8, msg_buffer_size=cap,
                          semantics=Semantics().robust())
    arrays = gen_heterogeneous_random_arrays(
        config, batch, instrs, dist="zipf", spread=float(spread),
        seed=0)
    kw = dict(block=block, cycles_per_call=k, snapshots=False,
              trace_window=window, gate=bool(gate))

    def timed(schedule):
        eng = PallasEngine(config, *arrays, schedule=schedule, **kw)
        t0 = time.perf_counter()
        eng.run(max_cycles=5_000_000)
        return eng, time.perf_counter() - t0

    # warm BOTH programs: the unscheduled multi-segment run and the
    # scheduler's n_seg=1 interval program are different lru-cache
    # entries, so each timed run needs its own compile out of the way.
    # fused=False pins this step to the host-barrier (PR-5) path it
    # has always measured; the fused path has its own three-way step.
    timed(None)
    timed(Schedule(fused=False))
    ref, ref_dt = timed(None)
    eng, dt = timed(Schedule(fused=False))
    exact = bool(np.array_equal(np.asarray(eng.state["scalars"]),
                                np.asarray(ref.state["scalars"])))
    print(json.dumps({
        "batch": batch, "instrs": instrs, "block": block, "k": k,
        "cap": cap, "window": window, "gate": gate, "spread": spread,
        "unscheduled_s": round(ref_dt, 3), "scheduled_s": round(dt, 3),
        "wall_speedup": round(ref_dt / dt, 2) if dt else None,
        "occupancy": eng.occupancy.as_dict(), "bit_exact": exact,
    }))
    return 0 if exact else 1


def measure_fused_occupancy_child(params) -> int:
    """--measure-fused-occupancy mode: heterogeneous (zipf) ensemble,
    three runs — unscheduled, PR-5 host-barrier scheduled, and fused
    single-program scheduled (optionally with packed state planes) —
    wall-clock + occupancy counters, one JSON line out.  Nonzero exit
    iff either scheduled run's per-system scalars plane differs from
    the unscheduled reference."""
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.ops.schedule import Schedule
    from hpa2_tpu.utils.trace import gen_heterogeneous_random_arrays

    batch, instrs, block, k, cap, window, gate, spread = params[:8]
    packed = bool(params[8]) if len(params) > 8 else False
    config = SystemConfig(num_procs=8, msg_buffer_size=cap,
                          semantics=Semantics().robust())
    arrays = gen_heterogeneous_random_arrays(
        config, batch, instrs, dist="zipf", spread=float(spread),
        seed=0)
    kw = dict(block=block, cycles_per_call=k, snapshots=False,
              trace_window=window, gate=bool(gate), packed=packed)

    def timed(schedule):
        eng = PallasEngine(config, *arrays, schedule=schedule, **kw)
        t0 = time.perf_counter()
        eng.run(max_cycles=5_000_000)
        return eng, time.perf_counter() - t0

    # three distinct programs, three compiles: warm each before timing
    for sched in (None, Schedule(fused=False), Schedule()):
        timed(sched)
    ref, ref_dt = timed(None)
    pr5, pr5_dt = timed(Schedule(fused=False))
    fus, fus_dt = timed(Schedule())
    scal = np.asarray(ref.state["scalars"])
    exact5 = bool(np.array_equal(np.asarray(pr5.state["scalars"]),
                                 scal))
    exactf = bool(np.array_equal(np.asarray(fus.state["scalars"]),
                                 scal))
    print(json.dumps({
        "batch": batch, "instrs": instrs, "block": block, "k": k,
        "cap": cap, "window": window, "gate": gate, "spread": spread,
        "packed": packed,
        "unscheduled_s": round(ref_dt, 3),
        "pr5_s": round(pr5_dt, 3), "fused_s": round(fus_dt, 3),
        "fused_speedup_vs_unscheduled":
            round(ref_dt / fus_dt, 2) if fus_dt else None,
        "fused_speedup_vs_pr5":
            round(pr5_dt / fus_dt, 2) if fus_dt else None,
        "pr5_occupancy": pr5.occupancy.as_dict(),
        "fused_occupancy": fus.occupancy.as_dict(),
        "bit_exact_pr5": exact5, "bit_exact_fused": exactf,
    }))
    return 0 if exact5 and exactf else 1


def measure_elision_child(params) -> int:
    """--measure-elision mode: zipf private-hot-set ensemble on the
    batched XLA engine (elision is an XLA-path knob; Pallas runs
    lockstep either way), elide=True vs elide=False wall-clock plus
    the device counters, one JSON line out.  Nonzero exit iff any
    state plane other than the two elision counters differs between
    the runs — the bit-identity contract, measured at scale.
    Params: batch instrs spread tail_bp (spread = hot-set max/min
    weight; tail_bp = uniform-tail fraction in basis points).  The
    batched jump is the MIN over every lane (the vmapped while loop
    is one joint program), so one lane's tail miss forces the whole
    ensemble to lockstep: at 32768 lanes any nonzero tail measures
    ~zero elision by construction.  The scale step therefore runs the
    pure hot-set variant (tail_bp=0); the tail-bearing single-system
    numbers live in PERF.md and tests/test_elision.py."""
    import dataclasses

    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig

    batch, instrs = params[0], params[1]
    spread = float(params[2]) if len(params) > 2 else 8.0
    tail = (params[3] if len(params) > 3 else 100) / 10_000.0
    config = SystemConfig(num_procs=8, semantics=Semantics().robust())

    # vectorized gen_hot_hit_zipf: the nested-Instr generator builds
    # Python objects per instruction — fine for tests, not for a
    # 32768-lane ensemble.  Same distribution: per-node slot-distinct
    # hot set with zipf-like weights, tail-fraction uniform addresses.
    rng = np.random.default_rng(0)
    n, t = config.num_procs, instrs
    h = min(config.cache_size, config.mem_size)
    w = np.arange(1, h + 1, dtype=np.float64) ** -(
        np.log(spread) / np.log(float(h)) if h > 1 else 0.0)
    hot = (np.arange(n) * config.mem_size)[None, :, None] + rng.choice(
        h, size=(batch, n, t), p=w / w.sum())
    tr_addr = np.where(
        rng.random((batch, n, t)) < tail,
        rng.integers(0, config.num_addresses, (batch, n, t)),
        hot).astype(np.int32)
    tr_op = (rng.random((batch, n, t)) < 0.3).astype(np.int32)
    tr_val = rng.integers(0, 256, (batch, n, t)).astype(np.int32)
    tr_len = np.full((batch, n), t, dtype=np.int32)

    import jax

    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.state import init_state_batched

    def timed(cfg):
        run = jax.jit(build_batched_run(cfg, max_cycles=1_000_000))
        st = init_state_batched(cfg, tr_op, tr_addr, tr_val, tr_len)
        jax.block_until_ready(run(st))  # compile + warmup
        t0 = time.perf_counter()
        out = jax.block_until_ready(run(st))
        return out, time.perf_counter() - t0

    on, on_dt = timed(config)
    off, off_dt = timed(dataclasses.replace(config, elide=False))
    exact = all(
        bool(np.array_equal(np.asarray(getattr(on, f)),
                            np.asarray(getattr(off, f))))
        for f in on._fields if f not in ("n_elided", "n_multi_hit"))
    cycles = int(np.sum(np.asarray(on.cycle)))
    elided = int(np.sum(np.asarray(on.n_elided)))
    print(json.dumps({
        "batch": batch, "instrs": instrs, "spread": spread,
        "tail": tail,
        "elide_s": round(on_dt, 3), "no_elide_s": round(off_dt, 3),
        "wall_speedup": round(off_dt / on_dt, 2) if on_dt else None,
        "simulated_cycles": cycles, "elided_cycles": elided,
        "multi_hit_retired": int(np.sum(np.asarray(on.n_multi_hit))),
        "step_reduction":
            round(cycles / (cycles - elided), 2) if cycles > elided
            else None,
        "bit_exact": exact,
    }))
    return 0 if exact else 1


def measure_nodeshard_child(params) -> int:
    """--measure-nodeshard mode: one system's node planes split over
    ``shards`` devices (NodeShardedPallasEngine, batched collective
    exchange), timed, with the measured cross-shard traffic.  With
    ``compare=1`` the same workload also runs on the single-chip
    kernel and the whole state must be bit-exact (nonzero exit
    otherwise); ``compare=0`` is for geometries one chip cannot hold.
    Params: procs batch instrs block k cap window gate shards compare
    [mode_idx] — mode_idx indexes EXCHANGE_MODES (-1 keeps the config
    default, a2a).
    """
    import dataclasses

    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops import exchange as xops
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    (procs, batch, instrs, block, k, cap, window, gate, shards,
     compare) = params[:10]
    mode_idx = params[10] if len(params) > 10 else -1
    config = SystemConfig(num_procs=procs, msg_buffer_size=cap,
                          max_instr_num=0,
                          semantics=Semantics().robust())
    if mode_idx >= 0:
        config = dataclasses.replace(
            config, exchange_mode=xops.EXCHANGE_MODES[mode_idx])
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=0)
    kw = dict(block=block, cycles_per_call=k, snapshots=False,
              trace_window=window, gate=bool(gate))

    def timed(build):
        eng = build()
        t0 = time.perf_counter()
        eng.run(max_cycles=5_000_000)
        return eng, time.perf_counter() - t0

    def mk_sharded():
        return NodeShardedPallasEngine(
            config, *arrays, node_shards=shards, **kw)

    timed(mk_sharded)  # compile + warm
    shd, shd_dt = timed(mk_sharded)
    xmsgs = shd.cross_shard_msgs
    stats = shd.stats()
    rec = {
        "procs": procs, "batch": batch, "instrs": instrs,
        "block": block, "k": k, "cap": cap, "window": window,
        "gate": gate, "node_shards": shards,
        "instructions": shd.instructions, "cycles": shd.cycle,
        "sharded_s": round(shd_dt, 3),
        "ops_per_sec": round(shd.instructions / shd_dt, 1),
        "cross_shard_msgs": xmsgs,
        "cross_shard_msgs_per_cycle": round(
            xmsgs / max(shd.cycle, 1), 2),
        "exchange_mode": config.exchange_mode,
        "collectives_per_cycle": xops.plan_collectives(
            xops.make_plan(shards, config.exchange_mode,
                           config.exchange_inner)),
        "exchange_slot_hwm": stats.get("exchange_slot_hwm", 0),
        "exchange_bytes_per_cycle": stats.get(
            "exchange_bytes_per_cycle", 0),
        "exchange_multicast_saved": stats.get(
            "exchange_multicast_saved", 0),
        "exchange_combined": stats.get("exchange_combined", 0),
    }
    exact = True
    if compare:
        def mk_single():
            return PallasEngine(config, *arrays, **kw)

        timed(mk_single)
        ref, ref_dt = timed(mk_single)
        exact = all(
            np.array_equal(np.asarray(v), np.asarray(shd.state[f]))
            for f, v in ref.state.items()
        )
        rec.update(
            single_chip_s=round(ref_dt, 3),
            sharded_over_single=round(ref_dt / shd_dt, 2)
            if shd_dt else None,
            bit_exact=exact,
        )
    print(json.dumps(rec))
    return 0 if exact else 1


def measure_nodeshard_elision_child(params) -> int:
    """--measure-nodeshard-elision mode: cycle elision across the
    node-shard mesh (the round-15 psum-min jump).  One system's node
    planes split over ``shards`` devices on the jax path
    (NodeShardedEngine), hot-set zipf workload, elide on vs off —
    dumps and cycle count must agree (nonzero exit otherwise) and the
    on-run must actually skip cycles.  Params: procs instrs shards.
    """
    import dataclasses

    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.parallel.sharding import NodeShardedEngine, make_mesh
    from hpa2_tpu.utils.trace import gen_hot_hit_zipf

    procs, instrs, shards = params[:3]
    config = SystemConfig(num_procs=procs,
                          semantics=Semantics().robust())
    traces = gen_hot_hit_zipf(config, instrs, seed=0)
    mesh = make_mesh(node_shards=shards)

    def timed(cfg):
        NodeShardedEngine(cfg, traces, mesh=mesh).run()  # warm
        eng = NodeShardedEngine(cfg, traces, mesh=mesh)
        t0 = time.perf_counter()
        eng.run()
        return eng, time.perf_counter() - t0

    on, on_dt = timed(config)
    off, off_dt = timed(dataclasses.replace(config, elide=False))
    exact = all(
        bool(np.array_equal(np.asarray(getattr(on.state, f)),
                            np.asarray(getattr(off.state, f))))
        for f in on.state._fields
        if f not in ("n_elided", "n_multi_hit"))
    cycles = int(on.state.cycle)
    elided = int(np.sum(np.asarray(on.state.n_elided)))
    print(json.dumps({
        "procs": procs, "instrs": instrs, "node_shards": shards,
        "elide_s": round(on_dt, 3), "no_elide_s": round(off_dt, 3),
        "wall_speedup": round(off_dt / on_dt, 2) if on_dt else None,
        "simulated_cycles": cycles, "elided_cycles": elided,
        "step_reduction":
            round(cycles / (cycles - elided), 2) if cycles > elided
            else None,
        "bit_exact": exact,
    }))
    return 0 if exact and elided > 0 else 1


def measure(step, batch, instrs, block, k, cap, window, gate,
            timeout_s=900, shards=1):
    params = [batch, instrs, block, k, cap, window, gate]
    if shards != 1:
        params.append(shards)
    argv = [os.path.abspath(__file__), "--measure"] + [
        str(x) for x in params
    ]
    return run_py(step, argv, timeout_s, argv=True)


def _write_tuning(since: str):
    """Pick the best successful kernel-shape measurement recorded at
    or after ``since`` (this session only — the JSONL is append-mode
    across sessions) and write it to BENCH_TUNING.json so the next
    bench.py run (including the driver's end-of-round one) uses the
    winning shape without a code edit.  Only sweeps of the bench
    workload shape (batch/instrs/cap) are eligible.  Never raises:
    a tuning failure must not abort the remaining session steps."""
    try:
        best = None
        with open(OUT_PATH) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                r = rec.get("result") or {}
                if (
                    rec.get("ok")
                    and rec.get("at", "") >= since
                    and isinstance(r, dict)
                    and r.get("ops_per_sec")
                    and "block" in r
                    and r.get("batch") == 32768
                    and r.get("instrs") == 128
                    and r.get("cap") == 16
                    # data-sharded sweeps measure a different thing
                    # (per-chip throughput x chips); the tuning file
                    # feeds the single-engine bench shape
                    and not r.get("data_shards")
                ):
                    if (
                        best is None
                        or r["ops_per_sec"] > best["ops_per_sec"]
                    ):
                        best = r
        if best is None:
            record("tuning", {
                "ok": False,
                "error": "no successful bench-shape sweep to tune from",
            })
            return
        tuning = {
            "block": best["block"], "window": best["window"],
            "k": best["k"], "gate": bool(best["gate"]),
            "from_ops_per_sec": best["ops_per_sec"],
        }
        with open(os.path.join(REPO, "BENCH_TUNING.json"), "w") as f:
            json.dump(tuning, f, indent=1)
            f.write("\n")
        record("tuning", {"ok": True, "result": tuning})
    except Exception as e:  # noqa: BLE001 - fault isolation per step
        try:
            record("tuning", {"ok": False, "error": str(e)[-300:]})
        except Exception:  # noqa: BLE001
            pass


_PROBE_CODE = (
    "import sys, jax; ds = jax.devices(); "
    "import json; print(json.dumps({'devices': str(ds)})); "
    "sys.exit(0 if any('tpu' in str(d).lower() for d in ds) else 3)"
)


def main() -> int:
    if sys.argv[1:2] == ["--measure"]:
        return measure_child([int(x) for x in sys.argv[2:10]])
    if sys.argv[1:2] == ["--measure-occupancy"]:
        return measure_occupancy_child(
            [int(x) for x in sys.argv[2:10]]
        )
    if sys.argv[1:2] == ["--measure-fused-occupancy"]:
        return measure_fused_occupancy_child(
            [int(x) for x in sys.argv[2:11]]
        )
    if sys.argv[1:2] == ["--measure-elision"]:
        return measure_elision_child(
            [int(x) for x in sys.argv[2:6]]
        )
    if sys.argv[1:2] == ["--measure-nodeshard"]:
        return measure_nodeshard_child(
            [int(x) for x in sys.argv[2:13]]
        )
    if sys.argv[1:2] == ["--measure-nodeshard-elision"]:
        return measure_nodeshard_elision_child(
            [int(x) for x in sys.argv[2:5]]
        )
    session_start = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    skip = set()
    for i, a in enumerate(sys.argv):
        if a == "--skip" and i + 1 < len(sys.argv):
            skip = set(sys.argv[i + 1].split(","))

    if "probe" not in skip:
        r = run_py("probe", _PROBE_CODE, timeout_s=300)
        if not r["ok"]:
            print("no TPU; aborting session", file=sys.stderr)
            return 1

    # the tunnel can wedge mid-session (it has, repeatedly): re-probe
    # cheaply before each expensive step and bail after two
    # consecutive step failures, so a dropped window costs minutes,
    # not the sum of every remaining step's timeout
    state = {"fails": 0}

    def gate(step_name):
        if state["fails"] >= 2:
            record(step_name, {"ok": False,
                               "error": "skipped: session aborted"})
            return False
        # same budget as the initial probe: a reprobe re-initializes
        # the full TPU client, which can legitimately take minutes
        r = run_py(f"{step_name}.reprobe", _PROBE_CODE, timeout_s=300)
        if not r["ok"]:
            state["fails"] = 99
            record(step_name, {"ok": False,
                               "error": "skipped: tunnel dropped"})
            return False
        return True

    def note(rec):
        state["fails"] = 0 if rec.get("ok") else state["fails"] + 1
        return rec

    if "vmemprobe" not in skip:
        # compile-only: cheap, and settles model-vs-compiler VMEM
        # agreement before any expensive timing step
        probe = os.path.join(REPO, "scripts", "probe_compile.py")
        for blk, win in ((512, 32), (1024, 8), (2048, 8)):
            nm = f"vmemprobe{blk}"
            if gate(nm):
                note(run_py(
                    nm,
                    [probe, "--block", str(blk), "--window", str(win)],
                    timeout_s=600, argv=True))

    if "bench" not in skip and gate("bench"):
        note(run_py("bench", [os.path.join(REPO, "bench.py")],
                    timeout_s=1800, argv=True))

    if "differential" not in skip and gate("differential"):
        note(run_py(
            "differential",
            [os.path.join(REPO, "scripts", "tpu_differential.py")],
            timeout_s=900, argv=True))

    if "sweep512" not in skip and gate("sweep512"):
        # the round-4 shipped shape (block 512, window 32, gate on)
        note(measure("sweep512", 32768, 128, 512, 128, 16, 32, 1))

    if "block1024" not in skip and gate("block1024"):
        # PERF.md lever 1: 1024 lanes, window 8 (trace plane 1/4),
        # gate off (no lax.cond carry doubling), k sized to the
        # per-window cycle need
        note(measure("block1024", 32768, 128, 1024, 64, 16, 8, 0))

    if "block2048" not in skip and gate("block2048"):
        # the next lane doubling, reachable only because the trace
        # plane streams from HBM (the budget model predicts ~1.3 MiB
        # of headroom at window 8, gate off)
        note(measure("block2048", 32768, 128, 2048, 64, 16, 8, 0))

    if "sweeps" not in skip:
        for nm, params in (
            ("sweep_b1024_w16", (32768, 128, 1024, 96, 16, 16, 0)),
            ("sweep_b1024_gate", (32768, 128, 1024, 64, 16, 8, 1)),
            ("sweep_b512_w8", (32768, 128, 512, 64, 16, 8, 0)),
            ("sweep_b2048_w8", (32768, 128, 2048, 64, 16, 8, 0)),
        ):
            if gate(nm):
                note(measure(nm, *params))

    if "tuning" not in skip:
        _write_tuning(session_start)

    if "scale4" not in skip and gate("scale4"):
        note(run_py(
            "scale4",
            [os.path.join(REPO, "scripts", "scale_runs.py"), "4"],
            timeout_s=1800, argv=True))
    if "scale5" not in skip and gate("scale5"):
        note(run_py(
            "scale5",
            [os.path.join(REPO, "scripts", "scale_runs.py"), "5"],
            timeout_s=1800, argv=True))

    if "sweep512_dp" not in skip and gate("sweep512_dp"):
        # the shipped shape with the ensemble split across every local
        # chip (shards=0 = all devices) — the per-chip multiplier is
        # this row's ops_per_sec over sweep512's
        note(measure("sweep512_dp", 32768, 128, 512, 128, 16, 32, 1,
                     shards=0))
    if "occupancy512" not in skip and gate("occupancy512"):
        # the occupancy scheduler on the shipped shape: zipf trace
        # lengths (8x max/median spread), scheduled vs unscheduled
        # wall-clock — the hardware read on what the block-segment
        # counters (tier-1-asserted on CPU) buy in real seconds
        note(run_py(
            "occupancy512",
            [os.path.abspath(__file__), "--measure-occupancy",
             "32768", "128", "512", "128", "16", "32", "1", "8"],
            timeout_s=1800, argv=True))

    if "fused_occupancy512" not in skip and gate("fused_occupancy512"):
        # the ISSUE-6 read: fused single-program scheduler (packed
        # planes on) vs the PR-5 host-barrier path vs unscheduled on
        # the shipped shape — how many real seconds removing the
        # n_intervals host barriers (and halving the VMEM rent) buys
        note(run_py(
            "fused_occupancy512",
            [os.path.abspath(__file__), "--measure-fused-occupancy",
             "32768", "128", "512", "128", "16", "32", "1", "8", "1"],
            timeout_s=2400, argv=True))

    if "serve512" not in skip and gate("serve512"):
        # ISSUE-10: the always-on serving loop at the shipped 32768
        # resident shape — sustained ops/sec + p50/p99 job latency
        # under Poisson and heavy-tail zipf-burst arrivals, plus the
        # pipelined-vs-serial split showing how much host staging the
        # overlap hides.  bench.py --serve runs its own TPU child
        # under the cached-compile env and emits the one JSON line.
        os.environ["HPA2_SERVE_RESIDENT"] = "32768"
        try:
            note(run_py(
                "serve512",
                [os.path.join(REPO, "bench.py"), "--serve"],
                timeout_s=3600, argv=True))
        finally:
            os.environ.pop("HPA2_SERVE_RESIDENT", None)

    if "serve_mt512" not in skip and gate("serve_mt512"):
        # ISSUE-14: the multi-tenant service plane at the shipped
        # 32768 resident shape — the capacity runs under fair-drr
        # admission, plus the bench's multi_tenant section (4 weighted
        # tenants with an interactive/standard/batch deadline mix:
        # per-tenant p50/p99 latency, tenant_share, deadline hit rate)
        os.environ["HPA2_SERVE_RESIDENT"] = "32768"
        os.environ["HPA2_SERVE_POLICY"] = "fair-drr"
        try:
            note(run_py(
                "serve_mt512",
                [os.path.join(REPO, "bench.py"), "--serve"],
                timeout_s=3600, argv=True))
        finally:
            os.environ.pop("HPA2_SERVE_RESIDENT", None)
            os.environ.pop("HPA2_SERVE_POLICY", None)

    if "failover512" not in skip and gate("failover512"):
        # ISSUE-16: the fault-tolerance supervisor at a served 512
        # resident shape — recovery overhead per failure kind (kill /
        # hang / poison at the same interval barrier), the byte-
        # identity check against the unfailed dumps, and the wire-
        # sever client blackout.  512 (not 32768): recovery replays
        # in-flight jobs, so the step measures migration latency, not
        # peak capacity — the kill row's overhead includes the
        # migration target's first jit compile.
        os.environ["HPA2_SERVE_RESIDENT"] = "512"
        os.environ["HPA2_FAILOVER_AT"] = "3"
        try:
            note(run_py(
                "failover512",
                [os.path.join(REPO, "bench.py"), "--failover"],
                timeout_s=3600, argv=True))
        finally:
            os.environ.pop("HPA2_SERVE_RESIDENT", None)
            os.environ.pop("HPA2_FAILOVER_AT", None)

    if "elision512" not in skip and gate("elision512"):
        # ISSUE-12: event-driven cycle elision at the shipped batch
        # shape on the XLA engine (the path the knob lives on) over
        # the zipf private-hot-set workload — elide on vs off
        # wall-clock, the device counters behind the ≥2x step
        # reduction, and the full-state bit-identity gate.  tail_bp=0:
        # the batched jump is a min over all 32768 lanes, so any
        # uniform tail would collapse joint silence to zero (see the
        # child docstring); the pure hot-set run is the shape elision
        # is built for
        note(run_py(
            "elision512",
            [os.path.abspath(__file__), "--measure-elision",
             "32768", "128", "8", "0"],
            timeout_s=1800, argv=True))

    if "topo512" not in skip and gate("topo512"):
        # ISSUE-11: the interconnect sensitivity study at a larger
        # storm than the shipped TOPO_r11.json default (the spec
        # engine anchors the numbers, so node count stays modest) —
        # rewrites TOPO_r11.json with indicative:true numbers plus
        # the per-topology spec<->jax agreement verdicts
        os.environ["HPA2_TOPO_NODES"] = "16"
        os.environ["HPA2_TOPO_ROUNDS"] = "24"
        try:
            note(run_py(
                "topo512",
                [os.path.join(REPO, "bench.py"), "--topology"],
                timeout_s=1800, argv=True))
        finally:
            os.environ.pop("HPA2_TOPO_NODES", None)
            os.environ.pop("HPA2_TOPO_ROUNDS", None)

    if "multichip" not in skip and gate("multichip"):
        # full data_shards ladder + bit-exactness gate; rewrites
        # MULTICHIP_r06.json with indicative:true pod-slice numbers
        note(run_py(
            "multichip",
            [os.path.join(REPO, "scripts", "scale_runs.py"),
             "multichip"],
            timeout_s=1800, argv=True))

    if "nodeshard" not in skip and gate("nodeshard"):
        # PR-7: a 64-node system split across 4 chips vs the same
        # system on one chip — bit-exactness gates the step, and the
        # measured cross-shard traffic is the ICI cost the targeted
        # exchange actually pays (the all_gather it replaced shipped
        # the whole candidate grid every cycle)
        note(run_py(
            "nodeshard",
            [os.path.abspath(__file__), "--measure-nodeshard",
             "64", "1024", "64", "512", "64", "16", "16", "0",
             "4", "1"],
            timeout_s=1800, argv=True))
        # the node_shards ladder (rewrites MULTICHIP_r07.json with
        # indicative:true numbers)
        note(run_py(
            "nodeshard_ladder",
            [os.path.join(REPO, "scripts", "scale_runs.py"),
             "nodeshard"],
            timeout_s=1800, argv=True))
        # the old-vs-new exchange A/B ladder (ISSUE-15) — on real ICI
        # this rewrites MULTICHIP_r08.json with indicative:true numbers
        note(run_py(
            "nodeshard_ab",
            [os.path.join(REPO, "scripts", "scale_runs.py"),
             "nodeshard_ab"],
            timeout_s=2400, argv=True))
        # the geometry the node axis exists for: 4096 simulated nodes,
        # more than one chip holds — sharded-only, no single-chip
        # reference (compare=0)
        note(run_py(
            "nodeshard4096",
            [os.path.abspath(__file__), "--measure-nodeshard",
             "4096", "8", "32", "8", "64", "16", "16", "0",
             "4", "0"],
            timeout_s=2400, argv=True))

    if "nodeshard_x2x4" not in skip and gate("nodeshard_x2x4"):
        # ISSUE-15: the PR-7 reference geometry again at 2 and 4 node
        # shards under the batched a2a schedule (mode_idx 1) plus a
        # butterfly x4 rung (mode_idx 2) — bit-exactness gates each
        # step, and the recorded collectives_per_cycle is the ICI
        # dispatch budget the new transport pays per simulated cycle
        for label, shards, mode_idx in (
            ("nodeshard_x2", "2", "1"),
            ("nodeshard_x4", "4", "1"),
            ("nodeshard_x4_butterfly", "4", "2"),
        ):
            note(run_py(
                label,
                [os.path.abspath(__file__), "--measure-nodeshard",
                 "64", "1024", "64", "512", "64", "16", "16", "0",
                 shards, "1", mode_idx],
                timeout_s=1800, argv=True))

    if "elided_nodeshard" not in skip and gate("elided_nodeshard"):
        # ISSUE-15: cycle elision across the shard mesh — the psum-min
        # jump must pay on a hot-set workload while staying bit-exact
        # with the lockstep sharded run (the child exits nonzero when
        # either fails)
        note(run_py(
            "elided_nodeshard",
            [os.path.abspath(__file__),
             "--measure-nodeshard-elision", "64", "256", "4"],
            timeout_s=1800, argv=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
