"""Micro-benchmarks of the op patterns inside the Pallas cycle kernel,
on real TPU — isolates where the per-cycle time goes.

Each kernel runs K iterations of one pattern over a [*, B] block in
VMEM and is timed per iteration.  Patterns:

  empty     fori over identity cond with the full-size carry
  deliver   J x (one-hot compare + select) on [N, cap, B)  (phase C)
  rw        R x one-hot read + W x one-hot write over [N, M, B] (phase A)
  scalar    the integer quiescence reduce + cond           (loop gate)
  rowops    P x elementwise ops on [N, B] rows             (handler math)
"""

import functools
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32
N, CAP, M, B = 8, 16, 16, 256
K = 256
J = 40


def bench(name, kernel_body, arrs, grid=32):
    """arrs: dict name -> np array [shape..., B*grid]."""
    shapes = {k: v.shape[:-1] for k, v in arrs.items()}
    names = list(arrs)

    def kernel(*refs):
        s = {nm: refs[i][:] for i, nm in enumerate(names)}
        s = jax.lax.fori_loop(0, K, kernel_body, s)
        for i, nm in enumerate(names):
            refs[len(names) + i][:] = s[nm]

    def spec(prefix):
        shape = tuple(prefix) + (B,)
        nd = len(shape)
        return pl.BlockSpec(shape, (lambda i, _nd=nd: (0,) * (_nd - 1) + (i,)),
                            memory_space=pltpu.VMEM)

    total = B * grid
    fn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec(shapes[nm]) for nm in names],
        out_specs=[spec(shapes[nm]) for nm in names],
        out_shape=[jax.ShapeDtypeStruct(tuple(shapes[nm]) + (total,), jnp.int32)
                   for nm in names],
        input_output_aliases={i: i for i in range(len(names))},
    )
    f = jax.jit(lambda *a: fn(*a))
    # donation (input_output_aliases) consumes buffers: fresh args per call
    warm = [jnp.asarray(v) for v in arrs.values()]
    out = f(*warm)
    jax.block_until_ready(out)
    timed = [jnp.asarray(v) for v in arrs.values()]
    jax.block_until_ready(timed)
    t0 = time.perf_counter()
    out = f(*timed)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    checksum = int(jnp.sum(out[0]))
    per_iter_us = dt / K / grid * 1e6
    print(json.dumps({"name": name, "us_per_iter_per_block": round(per_iter_us, 3),
                      "block_b": B, "grid": grid, "checksum": checksum,
                      "total_s": round(dt, 4)}), flush=True)


def main():
    total = B * 32
    rng = np.random.default_rng(0)
    mb = rng.integers(0, 1 << 27, (N, CAP, total), dtype=np.int32)
    mem = rng.integers(0, 256, (N, M, total), dtype=np.int32)
    rows = rng.integers(0, 128, (N, total), dtype=np.int32)
    cnt = np.zeros((N, total), np.int32)

    # --- empty loop with carry ---------------------------------------
    def empty(_, s):
        return s

    bench("empty", empty, {"mb": mb, "mem": mem, "rows": rows})

    # --- cond-gated identity (the quiescence gate pattern) -----------
    def scalar_gate(_, s):
        active = jnp.sum(s["rows"]) + jnp.sum(s["cnt"])
        return jax.lax.cond(active == 0, lambda x: x,
                            lambda x: {k: v + 0 for k, v in x.items()}, s)

    bench("scalar_gate", scalar_gate, {"rows": rows, "cnt": cnt})

    # --- delivery pattern: J x (compare + select + small) ------------
    def deliver(_, s):
        mb_ = s["mb"]
        acc = jnp.zeros((N, B), I32)
        iota_cap = jax.lax.broadcasted_iota(I32, (N, CAP, B), 1)
        iota_n = jax.lax.broadcasted_iota(I32, (N, B), 0)
        cnt_ = s["cnt"]
        for j in range(J):
            recv = (s["rows"][j % N] + j) & 7
            valid = ((s["rows"][(j + 1) % N] >> (j & 3)) & 1) == 1
            valid_nb = valid[None, :] & (iota_n == recv[None, :])
            pos = cnt_ + acc
            accepted = valid_nb & (pos < CAP)
            hot = (iota_cap == pos[:, None, :]) & accepted[:, None, :]
            w = s["rows"][j % N] * 3 + j
            mb_ = jnp.where(hot, w[None, None, :], mb_)
            acc = acc + accepted.astype(I32)
        return {"mb": mb_, "rows": s["rows"], "cnt": cnt_ + acc}

    bench("deliver40", deliver, {"mb": mb, "rows": rows, "cnt": cnt})

    # --- one-hot read/write over [N, M, B] (phase A state access) ----
    def rw(_, s):
        iota_m = jax.lax.broadcasted_iota(I32, (N, M, B), 1)
        mem_ = s["mem"]
        out_rows = s["rows"]
        for r in range(6):
            idx = (s["rows"] + r) & (M - 1)
            val = jnp.sum(jnp.where(iota_m == idx[:, None, :], mem_, 0),
                          axis=1)
            out_rows = out_rows + val
        for wri in range(3):
            idx = (out_rows + wri) & (M - 1)
            mask = (out_rows & 1) == 0
            hot = (iota_m == idx[:, None, :]) & mask[:, None, :]
            mem_ = jnp.where(hot, out_rows[:, None, :], mem_)
        return {"mem": mem_, "rows": out_rows & 127}

    bench("rw_9x", rw, {"mem": mem, "rows": rows})

    # --- row ops: P elementwise ops on [N, B] ------------------------
    def rowops(_, s):
        x = s["rows"]
        y = s["cnt"]
        for p in range(100):
            m_ = (x & 3) == (p & 3)
            y = jnp.where(m_, y + x, y)
            x = (x * 5 + 1) & 1023
        return {"rows": x, "cnt": y}

    bench("rowops300", rowops, {"rows": rows, "cnt": cnt})


if __name__ == "__main__":
    main()
