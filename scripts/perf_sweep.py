"""Quick on-TPU throughput measurement for the Pallas engine.

Usage: python scripts/perf_sweep.py [batch instrs block cycles_per_call]
Prints one JSON line per configuration.
"""

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def measure(batch, instrs, block, k, cap=16, window=32, gate=1, seed=0, ablate=frozenset()):
    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = SystemConfig(
        num_procs=8, msg_buffer_size=cap, semantics=Semantics().robust()
    )
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=seed)
    eng = PallasEngine(config, *arrays, block=block, cycles_per_call=k,
                       snapshots=False, trace_window=window,
                       gate=bool(gate), _ablate=ablate)
    t0 = time.perf_counter()
    eng.run()
    warm_dt = time.perf_counter() - t0
    eng2 = PallasEngine(config, *arrays, block=block, cycles_per_call=k,
                        snapshots=False, trace_window=window,
                       gate=bool(gate), _ablate=ablate)
    t0 = time.perf_counter()
    eng2.run()
    dt = time.perf_counter() - t0
    import numpy as np
    cycles = int(np.max(np.asarray(eng2.state["scalars"])[0]))
    print(json.dumps({
        "batch": batch, "instrs_per_core": instrs, "block": block, "cap": cap,
        "cycles_per_call": k, "window": window, "gate": gate, "instructions": eng2.instructions,
        "seconds": round(dt, 4), "warm_seconds": round(warm_dt, 1),
        "ops_per_sec": round(eng2.instructions / dt, 1),
        "cycles": cycles,
        "us_per_cycle": round(dt / cycles * 1e6, 2),
    }), flush=True)


def measure_ablate(batch, instrs, block, k, cap, window, names):
    """Time ablated (semantically wrong) kernels via full run()
    invocations — the only timing the axon tunnel reports honestly
    (async dispatch defers all cost to the final readback).  Ablated
    kernels never quiesce, so bound the run with max_cycles and count
    executed cycles from the on-device counter."""
    import numpy as np
    from hpa2_tpu.models.spec_engine import StallError
    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine, _SC_CYCLE
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = SystemConfig(
        num_procs=8, msg_buffer_size=cap, semantics=Semantics().robust()
    )
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=0)
    budget = 4 * k  # cycles per window segment before the stall bound

    def one_run():
        eng = PallasEngine(config, *arrays, block=block,
                           cycles_per_call=k, snapshots=False,
                           trace_window=window,
                           _ablate=frozenset(names))
        t0 = time.perf_counter()
        try:
            eng.run(max_cycles=budget)
        except StallError:
            pass
        dt = time.perf_counter() - t0
        cyc = int(np.max(np.asarray(eng.state["scalars"][_SC_CYCLE])))
        return dt, cyc

    one_run()  # compile + warm
    dt, cyc = one_run()
    print(json.dumps({"ablate": sorted(names), "batch": batch,
                      "block": block, "cap": cap, "window": window,
                      "run_s": round(dt, 3), "cycles_run": cyc,
                      "us_per_cycle": round(dt / max(cyc, 1) * 1e6, 2)}),
          flush=True)


if __name__ == "__main__":
    if sys.argv[1:2] == ["--ablate"]:
        names = [a for a in sys.argv[2:] if not a.isdigit()]
        nums = [int(a) for a in sys.argv[2:] if a.isdigit()]
        batch, instrs, block, k, cap, window = (
            nums + [8192, 128, 512, 128, 16, 32][len(nums):])
        measure_ablate(batch, instrs, block, k, cap, window, names)
    else:
        args = [int(x) for x in sys.argv[1:]]
        if args:
            measure(*args)
        else:
            measure(8192, 128, 128, 128)
