"""On-TPU differential: the Mosaic Pallas kernel vs the XLA engine on
the same random workload.  Exit 0 + JSON on agreement."""

import json
import sys

sys.path.insert(0, "/root/repo")


def main() -> int:
    import numpy as np
    import jax.numpy as jnp

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = SystemConfig(
        num_procs=8, msg_buffer_size=32, semantics=Semantics().robust()
    )
    batch, instrs = 128, 24
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=7)

    eng = PallasEngine(config, *arrays)
    assert not eng._interpret_active, "expected Mosaic path on TPU"
    eng.run()

    state = init_state_batched(config, *arrays)
    run = build_batched_run(config, max_cycles=100_000)
    out = run(state)

    mem = np.asarray(out.mem)
    dstate = np.asarray(out.dir_state)
    dsh = np.asarray(out.dir_sharers)[:, :, :, 0]
    caddr = np.asarray(out.cache_addr)
    cval = np.asarray(out.cache_val)
    cstate = np.asarray(out.cache_state)

    mism = 0
    for b in range(batch):
        for nd in eng.system_final_dumps(b):
            i = nd.proc_id
            okv = (
                nd.memory == [int(x) for x in mem[b, i]]
                and nd.dir_state == [int(x) for x in dstate[b, i]]
                and nd.dir_sharers == [int(x) for x in dsh[b, i]]
                and nd.cache_addr == [int(x) for x in caddr[b, i]]
                and nd.cache_value == [int(x) for x in cval[b, i]]
                and nd.cache_state == [int(x) for x in cstate[b, i]]
            )
            mism += 0 if okv else 1
    xi = int(jnp.sum(out.n_instr))
    pi = eng.instructions
    ok = mism == 0 and xi == pi
    print(json.dumps({"ok": ok, "node_mismatches": mism,
                      "instr_xla": xi, "instr_pallas": pi,
                      "batch": batch}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
