"""On-TPU differential: the Mosaic Pallas kernel vs the XLA engine on
the same random workload.  Exit 0 + JSON on agreement."""

import json
import sys

sys.path.insert(0, "/root/repo")


def main() -> int:
    import numpy as np
    import jax.numpy as jnp

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = SystemConfig(
        num_procs=8, msg_buffer_size=32, semantics=Semantics().robust()
    )
    batch, instrs = 128, 24
    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=7)

    eng = PallasEngine(config, *arrays)
    assert not eng._interpret_active, "expected Mosaic path on TPU"
    eng.run()

    state = init_state_batched(config, *arrays)
    run = build_batched_run(config, max_cycles=100_000)
    out = run(state)

    mism = []
    pairs = [
        ("mem", out.mem), ("dir_state", out.dir_state),
        ("cache_addr", out.cache_addr), ("cache_val", out.cache_val),
        ("cache_state", out.cache_state),
    ]
    for name, xla_arr in pairs:
        # XLA layout [B, N, ...] -> transposed [N, ..., B]
        x = np.moveaxis(np.asarray(xla_arr), 0, -1)
        p = np.asarray(eng.state[name])
        if x.shape != p.shape:
            x = x.reshape(p.shape)
        if not np.array_equal(x, p):
            mism.append(name)
    x_sh = np.moveaxis(np.asarray(out.dir_sharers), 0, -1)[:, :, 0, :]
    if not np.array_equal(x_sh, np.asarray(eng.state["dir_sharers"])):
        mism.append("dir_sharers")
    xi = int(jnp.sum(out.n_instr))
    pi = eng.instructions
    if xi != pi:
        mism.append(f"instr {xi} vs {pi}")
    print(json.dumps({"ok": not mism, "mismatches": mism,
                      "instructions": pi, "batch": batch}))
    return 0 if not mism else 1


if __name__ == "__main__":
    sys.exit(main())
