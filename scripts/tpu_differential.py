"""On-TPU differential: the Mosaic Pallas kernel vs the XLA engine on
the same random workloads — the reference 8-node geometry AND a
33-node split-plane geometry (two sharer words), so the wide-node path
is validated under the real Mosaic lowering, not just the interpreter.
Exit 0 + one JSON line on agreement."""

import json
import os
import sys

sys.path.insert(0, "/root/repo")


def _compare(tag, config, batch, instrs, seed):
    import numpy as np
    import jax.numpy as jnp

    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.pallas_engine import PallasEngine
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    arrays = gen_uniform_random_arrays(config, batch, instrs, seed=seed)

    eng = PallasEngine(config, *arrays)
    if not os.environ.get("HPA2_ALLOW_INTERPRET"):
        assert not eng._interpret_active, "expected Mosaic path on TPU"
    eng.run()

    state = init_state_batched(config, *arrays)
    run = build_batched_run(config, max_cycles=100_000)
    out = run(state)

    mem = np.asarray(out.mem)
    dstate = np.asarray(out.dir_state)
    # [B, N, M, W] uint32 words -> true python-int masks
    dshw = np.asarray(out.dir_sharers).astype(np.uint32)
    caddr = np.asarray(out.cache_addr)
    cval = np.asarray(out.cache_val)
    cstate = np.asarray(out.cache_state)

    def xla_sharers(b, i):
        return [
            sum(int(dshw[b, i, j, k]) << (32 * k)
                for k in range(dshw.shape[3]))
            for j in range(config.mem_size)
        ]

    mism = 0
    for b in range(batch):
        for nd in eng.system_final_dumps(b):
            i = nd.proc_id
            okv = (
                nd.memory == [int(x) for x in mem[b, i]]
                and nd.dir_state == [int(x) for x in dstate[b, i]]
                and nd.dir_sharers == xla_sharers(b, i)
                and nd.cache_addr == [int(x) for x in caddr[b, i]]
                and nd.cache_value == [int(x) for x in cval[b, i]]
                and nd.cache_state == [int(x) for x in cstate[b, i]]
            )
            mism += 0 if okv else 1
    xi = int(jnp.sum(out.n_instr))
    pi = eng.instructions
    return {
        "tag": tag, "ok": mism == 0 and xi == pi,
        # self-describing: an interpret-mode run (HPA2_ALLOW_INTERPRET
        # escape hatch) must never read as a Mosaic validation
        "interpret": bool(eng._interpret_active),
        "node_mismatches": mism, "instr_xla": xi, "instr_pallas": pi,
        "batch": batch,
    }


def main() -> int:
    from hpa2_tpu.config import Semantics, SystemConfig

    robust = Semantics().robust()
    results = [
        _compare(
            "8n-packed",
            SystemConfig(num_procs=8, msg_buffer_size=32,
                         semantics=robust),
            128, 24, 7,
        ),
        _compare(
            "33n-split",
            SystemConfig(num_procs=33, cache_size=4, mem_size=8,
                         msg_buffer_size=32, semantics=robust),
            16, 10, 11,
        ),
    ]
    ok = all(r["ok"] for r in results)
    print(json.dumps({"ok": ok, "geometries": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
