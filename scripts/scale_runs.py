"""BASELINE.json configs 4-5 at full size on the real TPU, plus the
multi-device ensemble-scaling ladder.

  config4:   256-node x ~1M-instr producer-consumer trace (8 sharer
             words — the scaling analog of the reference's 1-byte
             bitVector cap, assignment.c:49) on the XLA engine.
  config5:   1024-system ensemble x 10K instrs/core uniform-random on
             the Pallas engine (windowed traces); ``--data-shards N``
             splits the ensemble over N local devices
             (DataShardedPallasEngine).
  multichip: the data_shards ladder (1..all local devices) on one
             fixed ensemble, with a bit-exactness check of the
             sharded final state against the single-device run —
             writes MULTICHIP_r06.json.  On a CPU host it re-execs
             itself onto the virtual 8-device mesh and tags the
             numbers ``indicative: false`` (virtual devices share the
             host's cores; only the partition evidence transfers, the
             wall-clock does not).
  nodeshard: the node_shards ladder (1..min(num_procs, devices)) on
             one fixed workload — each system's node planes split
             over the mesh's ``node`` axis with the targeted
             cross-shard exchange — with a bit-exactness check
             against the single-device run and the measured
             cross-shard message rate per rung; writes
             MULTICHIP_r07.json (same CPU virtual-mesh conventions
             as ``multichip``).
  nodeshard_ab: the round-15 exchange A/B — every node_shards rung
             run under the old serial "pairwise" schedule AND the
             batched "a2a" default, both bit-exact vs the
             single-device kernel; writes MULTICHIP_r08.json.

Prints one JSON line per config for PERF.md.
"""

import json
import sys
import time

sys.path.insert(0, "/root/repo")

_MULTICHIP_PATH = "/root/repo/MULTICHIP_r06.json"
_NODESHARD_PATH = "/root/repo/MULTICHIP_r07.json"
_NODESHARD_AB_PATH = "/root/repo/MULTICHIP_r08.json"


def config4(instrs_per_core=4096):
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.ops.step import quiescent
    from hpa2_tpu.utils.trace import gen_producer_consumer_arrays

    config = SystemConfig(
        num_procs=256, msg_buffer_size=64,
        max_instr_num=0, semantics=Semantics().robust(),
    )
    arrays = gen_producer_consumer_arrays(config, 1, instrs_per_core)
    state = init_state_batched(config, *arrays)
    run = build_batched_run(config, max_cycles=2_000_000)
    out = jax.block_until_ready(run(state))  # compile+run once
    state = init_state_batched(config, *arrays)
    t0 = time.perf_counter()
    out = jax.block_until_ready(run(state))
    dt = time.perf_counter() - t0
    assert bool(jnp.all(jax.vmap(quiescent)(out))), "no quiescence"
    assert not bool(jnp.any(out.overflow))
    instrs = int(jnp.sum(out.n_instr))
    cycles = int(jnp.max(out.cycle))
    print(json.dumps({
        "config": "4: 256-node x 1M producer-consumer (xla)",
        "nodes": 256, "sharer_words": config.sharer_words,
        "instructions": instrs, "cycles": cycles,
        "seconds": round(dt, 2),
        "ops_per_sec": round(instrs / dt, 1),
    }), flush=True)


def _build_pallas(config, arrays, data_shards, **kw):
    if data_shards > 1:
        from hpa2_tpu.parallel.sharding import DataShardedPallasEngine

        return DataShardedPallasEngine(
            config, *arrays, data_shards=data_shards, **kw)
    from hpa2_tpu.ops.pallas_engine import PallasEngine

    return PallasEngine(config, *arrays, **kw)


def config5(batch=1024, instrs_per_core=10_000, data_shards=1,
            dist=None, spread=8.0, schedule=False):
    """``--trace-len-dist zipf`` swaps the uniform workload for
    heterogeneous per-system trace lengths and ``--schedule`` turns on
    the occupancy scheduler (ops/schedule.py) — together the config-5
    demo of live-lane compaction at scale, reporting the measured
    occupancy counters alongside the throughput."""
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import _SC_CYCLE
    from hpa2_tpu.utils.trace import (
        gen_heterogeneous_random_arrays,
        gen_uniform_random_arrays,
    )

    config = SystemConfig(
        num_procs=8, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    if dist:
        arrays = gen_heterogeneous_random_arrays(
            config, batch, instrs_per_core, dist=dist, spread=spread)
    else:
        arrays = gen_uniform_random_arrays(config, batch,
                                           instrs_per_core)
    kw = dict(block=512, cycles_per_call=128, snapshots=False,
              trace_window=32)
    if schedule:
        from hpa2_tpu.ops.schedule import Schedule

        kw["schedule"] = Schedule()

    def build():
        return _build_pallas(config, arrays, data_shards, **kw)

    build().run(max_cycles=5_000_000)  # compile + warm
    eng = build()
    t0 = time.perf_counter()
    eng.run(max_cycles=5_000_000)
    dt = time.perf_counter() - t0
    cycles = int(np.max(np.asarray(eng.state["scalars"][_SC_CYCLE])))
    rec = {
        "config": "5: 1024-system x 10K-instr ensemble (pallas)",
        "nodes": 8, "batch": batch,
        "instructions": eng.instructions, "cycles": cycles,
        "seconds": round(dt, 2),
        "ops_per_sec": round(eng.instructions / dt, 1),
    }
    if data_shards != 1:
        rec["data_shards"] = data_shards
    if dist:
        rec["trace_len_dist"] = {"dist": dist, "spread": spread}
    if schedule:
        rec["occupancy"] = eng.occupancy.as_dict()
    print(json.dumps(rec), flush=True)


def multichip(batch=32, instrs_per_core=32):
    """The data_shards scaling ladder for MULTICHIP_r06.json.

    On a real TPU slice the per-shard wall-clock is the pod-scaling
    headline; on CPU the 8 virtual devices share the host's physical
    cores, so only the structure (balanced partition + bit-exact
    state) is evidence and the record says ``indicative: false``.
    CPU interpret mode is also slow, so the CPU ladder runs a small
    ensemble.
    """
    import jax
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    platform = jax.devices()[0].platform
    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    n_dev = len(jax.devices())
    if not on_tpu and n_dev < 8:
        # CPU-only host: restart this process onto the virtual
        # 8-device mesh (exec replaces the image, so the stale jax
        # backend in THIS interpreter doesn't matter); no-op if the
        # flag was already set or we already re-execed
        from hpa2_tpu.hostenv import reexec_with_virtual_mesh

        reexec_with_virtual_mesh(8)
    if on_tpu:
        batch, instrs_per_core = 32768, 128
    config = SystemConfig(
        num_procs=8, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    arrays = gen_uniform_random_arrays(config, batch, instrs_per_core)
    kw = dict(block=512, cycles_per_call=128, snapshots=False,
              trace_window=32)

    ladder = [s for s in (1, 2, 4, 8, 16, 32) if s <= n_dev]
    rows = []
    ref_state = None
    bit_exact = True
    for shards in ladder:
        def build():
            return _build_pallas(config, arrays, shards, **kw)

        build().run(max_cycles=5_000_000)  # compile + warm
        eng = build()
        t0 = time.perf_counter()
        eng.run(max_cycles=5_000_000)
        dt = time.perf_counter() - t0
        if ref_state is None:
            ref_state = {f: np.asarray(v) for f, v in eng.state.items()}
        else:
            bit_exact = bit_exact and all(
                np.array_equal(ref_state[f], np.asarray(v))
                for f, v in eng.state.items()
            )
        rows.append({
            "data_shards": shards,
            "instructions": eng.instructions,
            "seconds": round(dt, 3),
            "ops_per_sec": round(eng.instructions / dt, 1),
        })
        print(json.dumps({"multichip_step": rows[-1]}), flush=True)

    base = rows[0]["ops_per_sec"]
    record = {
        "metric": "pallas_data_parallel_scaling",
        "unit": "RD/WR ops/sec",
        "platform": platform,
        "n_devices": n_dev,
        # CPU virtual-mesh wall-clock is NOT a scaling headline
        # (devices share the host cores) — same convention as the
        # bench's CPU smoke
        "indicative": on_tpu,
        "batch": batch,
        "instrs_per_core": instrs_per_core,
        "bit_exact_vs_single_device": bool(bit_exact),
        "shards": rows,
        "speedup_at_max_shards": round(rows[-1]["ops_per_sec"] / base, 2)
        if base else None,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(_MULTICHIP_PATH, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record), flush=True)
    assert bit_exact, "sharded run diverged from single-device state"


def nodeshard(batch=4, instrs_per_core=16):
    """The node_shards scaling ladder for MULTICHIP_r07.json: one
    fixed workload, node planes split over 1/2/4/... devices, final
    state bit-exact vs the single-device kernel at every rung, plus
    the measured cross-shard traffic (the ICI bytes the targeted
    exchange actually ships — the all_gather it replaced moved the
    whole candidate grid every cycle).

    Same conventions as ``multichip``: on CPU the virtual 8-device
    mesh proves structure, not wall-clock (``indicative: false``),
    and interpret mode keeps the CPU workload tiny.
    """
    import jax
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    platform = jax.devices()[0].platform
    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    n_dev = len(jax.devices())
    if not on_tpu and n_dev < 8:
        from hpa2_tpu.hostenv import reexec_with_virtual_mesh

        reexec_with_virtual_mesh(8)
    num_procs = 8
    if on_tpu:
        # one system bigger than a chip is the point: more nodes,
        # fewer lanes than the ensemble ladder
        num_procs, batch, instrs_per_core = 64, 1024, 64
    config = SystemConfig(
        num_procs=num_procs, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    arrays = gen_uniform_random_arrays(config, batch, instrs_per_core)
    kw = dict(block=512, cycles_per_call=64, snapshots=False,
              trace_window=16)

    def build(shards):
        if shards == 1:
            from hpa2_tpu.ops.pallas_engine import PallasEngine

            return PallasEngine(config, *arrays, **kw)
        from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine

        return NodeShardedPallasEngine(
            config, *arrays, node_shards=shards, **kw)

    ladder = [
        s for s in (1, 2, 4, 8, 16, 32)
        if s <= min(n_dev, num_procs)
    ]
    rows = []
    ref_state = None
    bit_exact = True
    for shards in ladder:
        build(shards).run(max_cycles=5_000_000)  # compile + warm
        eng = build(shards)
        t0 = time.perf_counter()
        eng.run(max_cycles=5_000_000)
        dt = time.perf_counter() - t0
        if ref_state is None:
            ref_state = {f: np.asarray(v) for f, v in eng.state.items()}
        else:
            # the sharded engine carries extra transient planes
            # (activeg/xmsgs/exchov); compare the architectural ones
            bit_exact = bit_exact and all(
                np.array_equal(v, np.asarray(eng.state[f]))
                for f, v in ref_state.items()
            )
        row = {
            "node_shards": shards,
            "instructions": eng.instructions,
            "cycles": eng.cycle,
            "seconds": round(dt, 3),
            "ops_per_sec": round(eng.instructions / dt, 1),
        }
        if shards > 1:
            from hpa2_tpu.ops import exchange as xops

            xmsgs = eng.cross_shard_msgs
            row["cross_shard_msgs"] = xmsgs
            row["cross_shard_msgs_per_cycle"] = round(
                xmsgs / max(eng.cycle, 1), 2)
            row["exchange_mode"] = config.exchange_mode
            row["collectives_per_cycle"] = xops.plan_collectives(
                xops.make_plan(shards, config.exchange_mode,
                               config.exchange_inner))
        rows.append(row)
        print(json.dumps({"nodeshard_step": row}), flush=True)

    record = {
        "metric": "pallas_node_shard_scaling",
        "unit": "RD/WR ops/sec",
        "platform": platform,
        "n_devices": n_dev,
        "indicative": on_tpu,
        "nodes": num_procs,
        "batch": batch,
        "instrs_per_core": instrs_per_core,
        "bit_exact_vs_single_device": bool(bit_exact),
        "shards": rows,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(_NODESHARD_PATH, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record), flush=True)
    assert bit_exact, "node-sharded run diverged from single-device state"


def nodeshard_ab(batch=4, instrs_per_core=16):
    """The round-15 A/B node_shards ladder for MULTICHIP_r08.json:
    every rung runs THREE times — ``exchange_mode="pairwise"`` (the
    serial 2*(D-1)-round schedule whose MULTICHIP_r07 curve went
    backwards) against the two round-15 schedules, the batched
    ``"a2a"`` default and the O(log D) ``"butterfly"`` — with the
    final state bit-exact against the single-device kernel in ALL
    modes at every rung (asserted), so the perf deltas are between
    byte-identical simulations.

    On the CPU virtual mesh one ``all_to_all`` dispatch costs several
    ``ppermute`` dispatches, so ``butterfly`` is the representative
    "new" arm there (``new_speedup_vs_pairwise`` takes the better of
    the two); on a real TPU slice a2a's two fused ICI collectives are
    the expected winner.

    Same conventions as ``nodeshard``: on CPU the virtual 8-device
    mesh proves structure and the relative collective-schedule cost
    (``indicative: false`` — devices share the host's cores); real
    ICI wall-clock needs a TPU slice.
    """
    import dataclasses

    import jax
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops import exchange as xops
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    platform = jax.devices()[0].platform
    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    n_dev = len(jax.devices())
    if not on_tpu and n_dev < 8:
        from hpa2_tpu.hostenv import reexec_with_virtual_mesh

        reexec_with_virtual_mesh(8)
    num_procs = 8
    if on_tpu:
        num_procs, batch, instrs_per_core = 64, 1024, 64
    base = SystemConfig(
        num_procs=num_procs, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    arrays = gen_uniform_random_arrays(base, batch, instrs_per_core)
    kw = dict(block=512, cycles_per_call=64, snapshots=False,
              trace_window=16)

    def build(shards, mode):
        config = dataclasses.replace(base, exchange_mode=mode)
        if shards == 1:
            from hpa2_tpu.ops.pallas_engine import PallasEngine

            return PallasEngine(config, *arrays, **kw)
        from hpa2_tpu.parallel.sharding import NodeShardedPallasEngine

        return NodeShardedPallasEngine(
            config, *arrays, node_shards=shards, **kw)

    def timed(shards, mode, reps=3):
        # best-of-N: a shared-host virtual mesh is noisy enough that a
        # single run can invert a rung's A/B ordering
        build(shards, mode).run(max_cycles=5_000_000)  # compile + warm
        eng, dt = None, float("inf")
        for _ in range(reps):
            cand = build(shards, mode)
            t0 = time.perf_counter()
            cand.run(max_cycles=5_000_000)
            t = time.perf_counter() - t0
            if t < dt:
                eng, dt = cand, t
        return eng, dt

    def timed_arms(shards, arms, reps=3):
        # interleaved best-of-N: cycle through the arms each rep so a
        # slow load drift on the shared host hits every arm equally
        # instead of biasing whichever ran last
        for _, mode in arms:
            build(shards, mode).run(max_cycles=5_000_000)  # warm
        engs, best = {}, {}
        for _ in range(reps):
            for label, mode in arms:
                cand = build(shards, mode)
                t0 = time.perf_counter()
                cand.run(max_cycles=5_000_000)
                t = time.perf_counter() - t0
                if t < best.get(label, float("inf")):
                    engs[label], best[label] = cand, t
        return engs, best

    ladder = [
        s for s in (2, 4, 8, 16, 32)
        if s <= min(n_dev, num_procs)
    ]
    ref, ref_dt = timed(1, "a2a")
    ref_state = {f: np.asarray(v) for f, v in ref.state.items()}
    single = {
        "instructions": ref.instructions,
        "seconds": round(ref_dt, 3),
        "ops_per_sec": round(ref.instructions / ref_dt, 1),
    }
    print(json.dumps({"nodeshard_ab_single": single}), flush=True)
    rows = []
    bit_exact = True
    for shards in ladder:
        row = {"node_shards": shards}
        arms = (("old_pairwise", "pairwise"),
                ("new_a2a", "a2a"),
                ("new_butterfly", "butterfly"))
        engs, best = timed_arms(shards, arms)
        for label, mode in arms:
            eng, dt = engs[label], best[label]
            exact = all(
                np.array_equal(v, np.asarray(eng.state[f]))
                for f, v in ref_state.items()
            )
            bit_exact = bit_exact and exact
            row[label] = {
                "exchange_mode": mode,
                "collectives_per_cycle": xops.plan_collectives(
                    xops.make_plan(shards, mode, 0)),
                "seconds": round(dt, 3),
                "ops_per_sec": round(eng.instructions / dt, 1),
                "cross_shard_msgs": eng.cross_shard_msgs,
                "bit_exact": exact,
            }
        old = max(row["old_pairwise"]["ops_per_sec"], 1e-9)
        row["a2a_speedup_vs_pairwise"] = round(
            row["new_a2a"]["ops_per_sec"] / old, 2)
        row["butterfly_speedup_vs_pairwise"] = round(
            row["new_butterfly"]["ops_per_sec"] / old, 2)
        row["new_speedup_vs_pairwise"] = max(
            row["a2a_speedup_vs_pairwise"],
            row["butterfly_speedup_vs_pairwise"])
        rows.append(row)
        print(json.dumps({"nodeshard_ab_step": row}), flush=True)

    record = {
        "metric": "pallas_node_shard_exchange_ab",
        "unit": "RD/WR ops/sec",
        "platform": platform,
        "n_devices": n_dev,
        "indicative": on_tpu,
        "nodes": num_procs,
        "batch": batch,
        "instrs_per_core": instrs_per_core,
        "single_device": single,
        "bit_exact_vs_single_device": bool(bit_exact),
        "shards": rows,
        # D=1 -> deepest-rung throughput ratio, old schedule vs the
        # best new one: the "curve collapse" the round fixes
        "collapse_d1_to_deepest": {
            "old_pairwise": round(
                single["ops_per_sec"]
                / max(rows[-1]["old_pairwise"]["ops_per_sec"], 1e-9),
                2),
            "new_best": round(
                single["ops_per_sec"] / max(
                    rows[-1]["new_a2a"]["ops_per_sec"],
                    rows[-1]["new_butterfly"]["ops_per_sec"], 1e-9),
                2),
        },
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(_NODESHARD_AB_PATH, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record), flush=True)
    assert bit_exact, "an A/B rung diverged from the single-device state"


def _arg_int(name, default):
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which == "multichip":
        multichip()
        sys.exit(0)
    if which == "nodeshard":
        nodeshard()
        sys.exit(0)
    if which == "nodeshard_ab":
        nodeshard_ab()
        sys.exit(0)
    shards = _arg_int("--data-shards", 1)
    if which in ("4", "both"):
        config4()
    if which in ("5", "both"):
        config5(data_shards=shards)
