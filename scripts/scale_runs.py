"""BASELINE.json configs 4-5 at full size on the real TPU.

  config4: 256-node x ~1M-instr producer-consumer trace (8 sharer
           words — the scaling analog of the reference's 1-byte
           bitVector cap, assignment.c:49) on the XLA engine.
  config5: 1024-system ensemble x 10K instrs/core uniform-random on
           the Pallas engine (windowed traces).

Prints one JSON line per config for PERF.md.
"""

import json
import sys
import time

sys.path.insert(0, "/root/repo")


def config4(instrs_per_core=4096):
    import jax
    import jax.numpy as jnp

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.engine import build_batched_run
    from hpa2_tpu.ops.state import init_state_batched
    from hpa2_tpu.ops.step import quiescent
    from hpa2_tpu.utils.trace import gen_producer_consumer_arrays

    config = SystemConfig(
        num_procs=256, msg_buffer_size=64,
        max_instr_num=0, semantics=Semantics().robust(),
    )
    arrays = gen_producer_consumer_arrays(config, 1, instrs_per_core)
    state = init_state_batched(config, *arrays)
    run = build_batched_run(config, max_cycles=2_000_000)
    out = jax.block_until_ready(run(state))  # compile+run once
    state = init_state_batched(config, *arrays)
    t0 = time.perf_counter()
    out = jax.block_until_ready(run(state))
    dt = time.perf_counter() - t0
    assert bool(jnp.all(jax.vmap(quiescent)(out))), "no quiescence"
    assert not bool(jnp.any(out.overflow))
    instrs = int(jnp.sum(out.n_instr))
    cycles = int(jnp.max(out.cycle))
    print(json.dumps({
        "config": "4: 256-node x 1M producer-consumer (xla)",
        "nodes": 256, "sharer_words": config.sharer_words,
        "instructions": instrs, "cycles": cycles,
        "seconds": round(dt, 2),
        "ops_per_sec": round(instrs / dt, 1),
    }), flush=True)


def config5(batch=1024, instrs_per_core=10_000):
    import numpy as np

    from hpa2_tpu.config import Semantics, SystemConfig
    from hpa2_tpu.ops.pallas_engine import PallasEngine, _SC_CYCLE
    from hpa2_tpu.utils.trace import gen_uniform_random_arrays

    config = SystemConfig(
        num_procs=8, msg_buffer_size=16, max_instr_num=0,
        semantics=Semantics().robust(),
    )
    arrays = gen_uniform_random_arrays(config, batch, instrs_per_core)

    def build():
        return PallasEngine(config, *arrays, block=512,
                            cycles_per_call=128, snapshots=False,
                            trace_window=32)

    build().run(max_cycles=5_000_000)  # compile + warm
    eng = build()
    t0 = time.perf_counter()
    eng.run(max_cycles=5_000_000)
    dt = time.perf_counter() - t0
    cycles = int(np.max(np.asarray(eng.state["scalars"][_SC_CYCLE])))
    print(json.dumps({
        "config": "5: 1024-system x 10K-instr ensemble (pallas)",
        "nodes": 8, "batch": batch,
        "instructions": eng.instructions, "cycles": cycles,
        "seconds": round(dt, 2),
        "ops_per_sec": round(eng.instructions / dt, 1),
    }), flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("4", "both"):
        config4()
    if which in ("5", "both"):
        config5()
