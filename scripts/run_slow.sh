#!/usr/bin/env bash
# Slow tier — everything the tier-1 gate excludes with -m 'not slow':
#
#   * the oversubscribed TSan workloads (2x-cores OMP threads over a
#     32-node system; tests/test_sanitizers.py)
#   * the large randomized differential sweeps (SLOW_GEOMETRIES in
#     tests/test_random_differential.py: deeper traces, wider node
#     counts, both split-plane widths)
#   * the 64-node SW=3 split-plane differential (~5 min interpret
#     mode; tests/test_pallas_engine.py)
#   * the cross-protocol analyzer fuzz: seeded random table
#     corruptions per protocol, each caught statically or by a
#     backend probe diff (tests/test_protocol_fuzz.py)
#
# Run on demand (pre-release, after touching the native OMP engine or
# the pallas sv_* helpers) — not part of the per-session gate.  Budget
# ~20-30 min.  Extra args pass through to pytest (e.g. -k tsan).
set -o pipefail
cd "$(dirname "$0")/.."

# build the TSan binary up front so a missing toolchain is reported
# once here, instead of as per-test skips that are easy to miss
if ! make -C native tsan >/dev/null 2>&1; then
    echo "WARNING: TSan build unavailable; sanitizer tests will skip" >&2
fi

exec env JAX_PLATFORMS=cpu python -m pytest tests/ -v -m slow \
    --continue-on-collection-errors -p no:cacheprovider "$@"
